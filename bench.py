"""Driver benchmark: the BASELINE.md target matrix, one JSON line.

Workloads (BASELINE.md "Targets" table):

- ``fused_suite_update_throughput`` (headline) — one batch of ``(B, C)``
  probabilities + targets per step through a 4-metric classification suite
  (Accuracy / F1 macro / ConfusionMatrix / Precision macro), the whole suite
  as ONE jitted XLA computation with donated state.
- ``fid_wallclock`` — full FID cycle (update incl. Flax InceptionV3 forward
  on 299x299 uint8 images, + compute with the covariance/sqrtm statistics).
- ``coco_map_wallclock`` — COCO-style MeanAveragePrecision update+compute
  over realistic per-image detections.
- ``per_step_overhead`` — per-step metric cost through the module API: the
  batched ``forward_many`` path (one `lax.scan` dispatch per
  ``MANY_STEPS``-step chunk) as the headline value, with the eager
  fused-forward steps/s and the measured backend sync/submission floor
  reported alongside.

Baselines: the mounted reference (`/root/reference/src`, TorchMetrics) on
torch-CPU — labeled in the output; no CUDA exists in this environment. FID's
reference needs torch-fidelity (absent), so its baseline is the in-repo
torch mirror of the identical architecture + scipy-sqrtm statistics, the
closest runnable stand-in (labeled "torch-cpu-mirror").

Prints exactly ONE JSON line; the driver reads metric/value/unit/vs_baseline
and the full per-workload detail rides along under "workloads":
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
     "baseline_hardware": ..., "workloads": {...}}
"""
from __future__ import annotations

import json
import os
import sys
import time
import warnings

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/metrics_tpu_jax_cache")

BATCH, NUM_CLASSES, STEPS, WARMUP, TRIALS = 8192, 128, 50, 5, 3

# BENCH_SMOKE=1 shrinks every workload to seconds-scale so CI can validate the
# harness end to end (same code paths, same JSON schema) without the timed
# runs being meaningful. Smoke numbers must never be recorded as results.
SMOKE = os.environ.get("BENCH_SMOKE", "") not in ("", "0")
if SMOKE:
    BATCH, STEPS, WARMUP, TRIALS = 256, 3, 1, 1


_REPO_DIR = os.path.dirname(os.path.abspath(__file__))


def _latency_percentiles(step, n: int, setup=None) -> dict:
    """Per-call latency percentiles (ms) over one extra ``n``-call pass,
    accumulated through the SAME full-lifetime histogram class the telemetry
    plane scrapes (``telemetry.LatencyHistogram``) — every percentile this
    bench publishes is bucket-interpolated exactly the way
    ``latency_stats()`` / ``prometheus_text()`` compute theirs, so a bench
    row and a production scrape are comparable numbers. Mean-of-best
    throughput hides the tail; these columns are what
    ``tools/sweep_regress.py``'s distribution-aware gate compares."""
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    from metrics_tpu.ops.telemetry import LatencyHistogram

    h = LatencyHistogram()
    for _ in range(n):
        if setup is not None:
            setup()  # untimed per-call staging (e.g. the stride updates a window close consumes)
        t0 = time.perf_counter()
        step()
        h.observe(time.perf_counter() - t0)
    s = h.stats()
    # ONE latency_ms schema across bench.py and tools/bench_sweep.py rows
    # (ms values under p50/p95/p99/max) — tools/sweep_regress.py's
    # distribution gate reads exactly these keys
    return {
        "p50": round(s["p50_s"] * 1000.0, 4),
        "p95": round(s["p95_s"] * 1000.0, 4),
        "p99": round(s["p99_s"] * 1000.0, 4),
        "max": round(s["max_s"] * 1000.0, 4),
        "n": int(s["count"]),
    }


def _reference():
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    from tests.helpers.reference_oracle import get_reference

    return get_reference()


def _make_data(seed: int = 0):
    rng = np.random.RandomState(seed)
    logits = rng.randn(BATCH, NUM_CLASSES).astype(np.float32)
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, size=(BATCH,))
    return probs, target


# ------------------------------------------------------- fused suite (headline)

def bench_suite_ours(probs: np.ndarray, target: np.ndarray) -> tuple:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision

    suite = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="macro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "confmat": ConfusionMatrix(num_classes=NUM_CLASSES),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    init, update, compute = suite.as_functions()
    states = init()
    fused_update = jax.jit(update, donate_argnums=(0,))

    p = jnp.asarray(probs)
    t = jnp.asarray(target)
    for _ in range(WARMUP):
        states = fused_update(states, p, t)
    jax.block_until_ready(states)

    # best of TRIALS: host<->device dispatch latency is noisy on tunneled
    # accelerators; the minimum elapsed time reflects the device's capability
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(STEPS):
            states = fused_update(states, p, t)
        jax.block_until_ready(states)
        best = min(best, time.perf_counter() - start)
    # per-step dispatch-latency distribution (one extra pass): per-call wall
    # time of the fused donated-state dispatch, final sync outside the timed
    # calls — the tail (queue hiccups, tunnel jitter) the best-of mean hides
    box = {"st": states}

    def _step():
        box["st"] = fused_update(box["st"], p, t)

    lat = _latency_percentiles(_step, STEPS)
    jax.block_until_ready(box["st"])

    # roofline columns (ISSUE 12): join the fused program's XLA cost analysis
    # with a device-INCLUSIVE per-step wall (every call blocked — the same
    # measurement the engine's sampled device probes land) into achieved
    # FLOP/s, achieved bytes/s and a bound classification against the
    # calibrated machine peaks — the evidence for WHY the row is as fast as
    # it is, not just how fast
    roofline = {}
    try:
        from metrics_tpu.ops import engine as _engine

        # lower through the ALREADY-jitted donated wrapper: same cache, same
        # donation configuration as the measured program — no second compile
        compiled = fused_update.lower(box["st"], p, t).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        analysis = {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
        }

        def _blocked_step():
            box["st"] = fused_update(box["st"], p, t)
            jax.block_until_ready(box["st"])

        n_probe = max(5, STEPS // 2)
        dev = _latency_percentiles(_blocked_step, n_probe)
        device_block = {
            "count": dev["n"],
            "p50_s": dev["p50"] / 1000.0,
            "sum_s": dev["p50"] / 1000.0 * max(1, dev["n"]),
        }
        roofline = _engine._roofline_row(
            analysis, device_block, lat["p50"] / 1000.0, _engine.roofline_peaks()
        )
    except Exception:  # noqa: BLE001 — a bench column must never kill the run
        pass
    _ = compute(box["st"])
    return STEPS * BATCH / best, lat, roofline


def bench_suite_reference(probs: np.ndarray, target: np.ndarray) -> float:
    tm = _reference()
    if tm is None:
        return 0.0
    import torch

    suite = [
        tm.Accuracy(num_classes=NUM_CLASSES, average="macro"),
        tm.F1Score(num_classes=NUM_CLASSES, average="macro"),
        tm.ConfusionMatrix(num_classes=NUM_CLASSES),
        tm.Precision(num_classes=NUM_CLASSES, average="macro"),
    ]
    device = "cuda" if torch.cuda.is_available() else "cpu"
    p = torch.tensor(probs, device=device)
    t = torch.tensor(target, device=device)
    suite = [m.to(device) for m in suite]

    for _ in range(WARMUP):
        for m in suite:
            m.update(p, t)
    if device == "cuda":
        torch.cuda.synchronize()
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(STEPS):
            for m in suite:
                m.update(p, t)
        if device == "cuda":
            torch.cuda.synchronize()
        best = min(best, time.perf_counter() - start)
    _ = [m.compute() for m in suite]
    return STEPS * BATCH / best


# --------------------------------------------------------------- FID wall-clock

FID_IMAGES, FID_BATCHES = (2, 1) if SMOKE else (16, 2)


def _fid_data():
    rng = np.random.RandomState(7)
    real = [rng.randint(0, 256, (FID_IMAGES, 3, 299, 299), dtype=np.uint8) for _ in range(FID_BATCHES)]
    fake = [rng.randint(0, 256, (FID_IMAGES, 3, 299, 299), dtype=np.uint8) for _ in range(FID_BATCHES)]
    return real, fake


def bench_fid_ours(real, fake) -> float:
    """Seconds per full FID cycle (2x2 batches of 16 images + compute)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.image.generative import FrechetInceptionDistance

    fid = FrechetInceptionDistance(feature=2048, allow_random_weights=True)
    # pre-place like every other workload: generated images are model
    # outputs already on device; timing their host->device transfer would
    # measure tunnel latency, not the metric
    real_d = [jnp.asarray(r) for r in real]
    fake_d = [jnp.asarray(f) for f in fake]
    jax.block_until_ready((real_d, fake_d))

    def cycle():
        fid.reset()
        for r, f in zip(real_d, fake_d):
            fid.update(r, real=True)
            fid.update(f, real=False)
        return float(fid.compute())

    cycle()  # compile warmup
    best = float("inf")
    for _ in range(2):
        start = time.perf_counter()
        cycle()
        best = min(best, time.perf_counter() - start)
    return best


def bench_fid_baseline(real, fake) -> float:
    """Torch mirror of the identical architecture + scipy-sqrtm statistics."""
    import torch

    from tests.helpers.torch_mirrors import TorchInceptionMirror, randomize_inception_

    mirror = TorchInceptionMirror()
    randomize_inception_(mirror)

    def features(batches):
        out = []
        with torch.no_grad():
            for b in batches:
                x = torch.from_numpy(b).float() / 255.0 * 2.0 - 1.0
                out.append(mirror(x)["2048"].numpy())
        return np.concatenate(out)

    def cycle():
        import scipy.linalg

        r, f = features(real).astype(np.float64), features(fake).astype(np.float64)
        mu1, mu2 = r.mean(0), f.mean(0)
        cov1, cov2 = np.cov(r, rowvar=False), np.cov(f, rowvar=False)
        covmean = scipy.linalg.sqrtm(cov1 @ cov2)
        if np.iscomplexobj(covmean):
            covmean = covmean.real
        return float((mu1 - mu2) @ (mu1 - mu2) + np.trace(cov1) + np.trace(cov2) - 2 * np.trace(covmean))

    cycle()
    best = float("inf")
    for _ in range(2):
        start = time.perf_counter()
        cycle()
        best = min(best, time.perf_counter() - start)
    return best


# ---------------------------------------------------------- COCO mAP wall-clock

MAP_IMAGES = 4 if SMOKE else 100


def bench_map_ours(batches) -> float:
    import jax
    import jax.numpy as jnp

    import metrics_tpu as mt

    # pre-place detections on device, like every other workload here: in a
    # real eval loop they are model outputs already resident on device, so
    # timing per-image host->device transfers would measure the tunnel's
    # (highly variable) transfer latency instead of the metric
    placed = [
        (
            [dict(boxes=jnp.asarray(det["boxes"]), scores=jnp.asarray(det["scores"]), labels=jnp.asarray(det["labels"]))],
            [dict(boxes=jnp.asarray(gt["boxes"]), labels=jnp.asarray(gt["labels"]))],
        )
        for det, gt in batches
    ]
    jax.block_until_ready(placed)

    def cycle():
        metric = mt.MeanAveragePrecision()
        for det_list, gt_list in placed:
            metric.update(det_list, gt_list)
        return float(metric.compute()["map"])

    cycle()
    start = time.perf_counter()
    cycle()
    return time.perf_counter() - start


def bench_map_baseline(batches) -> float:
    from tools.bench_map import _install_torchvision_shim

    tm = _reference()
    if tm is None:
        return 0.0
    import torch

    _install_torchvision_shim()
    import torchmetrics.detection.mean_ap as ref_map_mod
    import torchvision.ops as tv_ops

    ref_map_mod._TORCHVISION_GREATER_EQUAL_0_8 = True
    ref_map_mod.box_area = tv_ops.box_area
    ref_map_mod.box_iou = tv_ops.box_iou
    ref_map_mod.box_convert = tv_ops.box_convert

    def cycle():
        metric = ref_map_mod.MeanAveragePrecision()
        for det, gt in batches:
            metric.update(
                [dict(boxes=torch.from_numpy(det["boxes"]), scores=torch.from_numpy(det["scores"]), labels=torch.from_numpy(det["labels"]))],
                [dict(boxes=torch.from_numpy(gt["boxes"]), labels=torch.from_numpy(gt["labels"]))],
            )
        return float(metric.compute()["map"])

    cycle()
    start = time.perf_counter()
    cycle()
    return time.perf_counter() - start


# --------------------------------------------------------- per-step overhead

# must match the floor probes' per-trial call count (`_min_ms_per_call`
# n=200): each trial ends in ONE blocking sync (~110 ms post-read through
# the tunnel), so the row and its floor comparator have to amortize that
# sync over the SAME number of steps — at 30 steps the sync alone added
# ~3.6 ms/step to the row while the probe amortized it to 0.55 ms, and
# `floor_bound_factor` mostly measured the protocol mismatch
OVERHEAD_STEPS = 8 if SMOKE else 200


def bench_overhead_ours() -> float:
    """Steps/s of the module-API forward (integration hot path).

    Uses the documented remote-backend configuration
    (METRICS_TPU_VALIDATION=first): first call validates eagerly, later calls
    run the fused single-dispatch forward program."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import engine
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    # this row measures the PER-CALL fused dispatch (the PR-1 behavior and
    # the METRICS_TPU_DEFER=0 escape hatch); the deferred_per_step row
    # measures the same loop with the queue on
    engine.set_deferred_dispatch(False)
    try:
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 2, BATCH))
        metric = Accuracy()
        for _ in range(3):
            metric(p, t)
        jax.block_until_ready(metric.correct)
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(OVERHEAD_STEPS):
                metric(p, t)
            jax.block_until_ready(metric.correct)
            best = min(best, time.perf_counter() - start)
        return OVERHEAD_STEPS / best
    finally:
        engine.set_deferred_dispatch(True)


def bench_dispatch_floor() -> dict:
    """The tunneled backend's hard per-step cost model, measured empty.

    After the first device->host value read of a session (any
    ``float(metric.compute())`` — something every real eval loop does), the
    backend stops overlapping dependent work with the host: program
    SUBMISSION stays ~microseconds, but every blocking synchronization
    (``block_until_ready`` / a value read) costs one full network round trip
    — measured here with an add-one program carrying a scalar. That round
    trip, not metric code, is the floor under any loop that synchronizes per
    step; amortizing it across a chunk is what ``forward_many`` is for.
    """
    import jax
    import jax.numpy as jnp

    def _min_ms_per_call(step, n=200):
        """min-over-TRIALS ms/call of a chained step, final sync amortized —
        the one timing protocol for every per-program floor probe here."""
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            out = None
            for _ in range(n):
                out = step()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - start) / n * 1000.0)
        return best

    f = jax.jit(lambda s: s + 1)
    s = f(jnp.int32(0))
    float(s)  # force the post-read regime (no-op if already in it)
    s = f(s)
    jax.block_until_ready(s)
    start = time.perf_counter()
    for _ in range(100):
        s = f(s)
    submission_ms = (time.perf_counter() - start) / 100 * 1000.0
    jax.block_until_ready(s)

    # steady-state per-PROGRAM cost of a minimal chained jitted step: the
    # absolute floor under ANY eager loop, however small the program
    box = {"s": s}

    def _empty_step():
        box["s"] = f(box["s"])
        return box["s"]

    program_ms = _min_ms_per_call(_empty_step)
    sync_ms = float("inf")
    for _ in range(TRIALS):
        s = f(s)
        start = time.perf_counter()
        jax.block_until_ready(s)
        sync_ms = min(sync_ms, (time.perf_counter() - start) * 1000.0)

    # SHAPE-MATCHED floor: a chained program with EXACTLY the benched
    # `eager_per_step` metric's buffer profile — its state pytree plus the
    # (BATCH,) input and scalar batch value, compiled with the SAME
    # donated-state aliasing the dispatch-engine forward uses. Each extra
    # output buffer adds tunnel traffic, so this (not the scalar add-one) is
    # the honest comparator for the fused forward step.
    from metrics_tpu import Accuracy
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    m = Accuracy()
    rng = np.random.RandomState(0)
    v = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    m(v, jnp.asarray(rng.randint(0, 2, BATCH)))
    state0 = {k: jnp.copy(a) for k, a in m.metric_state.items()}  # donation-safe copies

    g = jax.jit(
        lambda st, x: ({k: a + 1 for k, a in st.items()}, x.mean()), donate_argnums=(0,)
    )
    sbox = {"st": state0}

    def _shaped_step():
        sbox["st"], val = g(sbox["st"], v)
        return val

    _shaped_step()
    shaped_ms = _min_ms_per_call(_shaped_step)
    return {
        "submission_ms_per_dispatch": submission_ms,
        "sync_roundtrip_ms": sync_ms,
        "program_roundtrip_ms": program_ms,
        "shaped_program_roundtrip_ms": shaped_ms,
    }


def bench_bootstrap_shaped_floor() -> dict:
    """Genuinely-shaped floor probes for the BootStrapper one-program paths
    (VERDICT round-5 Next #1: the old add-one probe was "substantially
    smaller" than the real program, so its floor_bound_factor compared
    apples to oranges).

    Both probes carry the REAL programs' full buffer profile — the stacked
    per-clone state leaves, the (num_bootstraps, BATCH) draw matrix, the
    (BATCH,) data operands, and (poisson) the per-row delta intermediates of
    the vmapped-update + weight-contraction pipeline — with a trivial
    one-op update in place of the metric kernel, donated state, chained
    steps, final sync amortized: the honest lower bound on what ANY
    weighted-row/vmapped-clone program costs per step on this backend.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.wrappers._fanout import weighted_state_apply

    num_bootstraps = 10  # the reference default the sweep's slow row uses
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.randn(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randn(BATCH).astype(np.float32))
    # MeanSquaredError's state profile: one float sum + one int64/32 count.
    # Fresh buffers per clone per probe: the chained programs donate their
    # state, so no buffer may appear twice (or be reused across probes).
    def fresh_states():
        return [
            {
                "sum_squared_error": jnp.zeros((), jnp.float32),
                "total": jnp.zeros((), jnp.int32),
            }
            for _ in range(num_bootstraps)
        ]

    def _min_ms(step, n=200):
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            out = None
            for _ in range(n):
                out = step()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - start) / n * 1000.0)
        return best

    # ---- poisson weighted-row shape: per-row deltas + count contraction
    def upd_like(state, pr, tr):
        bump = (pr - tr).sum()
        return {
            "sum_squared_error": state["sum_squared_error"] + bump,
            "total": state["total"] + jnp.asarray(pr.shape[0], jnp.int32),
        }

    def poisson_program(states, w, pr, tr):
        def one_row(row):
            ra = jax.tree.map(lambda x: x[None], row)
            new = upd_like({k: jnp.zeros_like(v) for k, v in states[0].items()}, *ra)
            return new

        deltas = jax.vmap(one_row)((pr, tr))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        new = weighted_state_apply(stacked, deltas, w)
        return [jax.tree.map(lambda x: x[i], new) for i in range(len(states))]

    poisson = jax.jit(poisson_program, donate_argnums=(0,))
    counts = jnp.asarray(rng.poisson(1, size=(num_bootstraps, BATCH)).astype(np.int32))
    pbox = {"st": fresh_states()}

    def _poisson_step():
        pbox["st"] = poisson(pbox["st"], counts, p, t)
        return pbox["st"]

    _poisson_step()
    poisson_ms = _min_ms(_poisson_step)

    # ---- multinomial shape: vmapped per-clone take + trivial update
    def multinomial_program(states, idx, pr, tr):
        def one(state, rows):
            ra = jnp.take(pr, rows, axis=0)
            rb = jnp.take(tr, rows, axis=0)
            return upd_like(state, ra, rb)

        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        out = jax.vmap(one)(stacked, idx)
        return [jax.tree.map(lambda x: x[i], out) for i in range(len(states))]

    multinomial = jax.jit(multinomial_program, donate_argnums=(0,))
    draws = jnp.asarray(rng.randint(0, BATCH, size=(num_bootstraps, BATCH)))
    mbox = {"st": fresh_states()}

    def _multinomial_step():
        mbox["st"] = multinomial(mbox["st"], draws, p, t)
        return mbox["st"]

    _multinomial_step()
    multinomial_ms = _min_ms(_multinomial_step)
    return {
        "poisson_weighted_row_floor_ms": poisson_ms,
        "multinomial_vmap_floor_ms": multinomial_ms,
        "num_bootstraps": num_bootstraps,
        "note": (
            "chained donated-state programs with the real one-program "
            "bootstrap paths' exact buffer profile (stacked clone states, "
            "draw matrix, per-row delta intermediates) and a one-op update "
            "kernel — the apples-to-apples comparator for the sweep's "
            "BootStrapper rows' floor_bound_factor"
        ),
    }


MANY_STEPS = 32 if SMOKE else 4096  # larger chunks amortize the sync round
# trip further: measured 9.4k steps/s at 1024, 27k at 2048, 44k at 4096
# (same workload)


def bench_overhead_batched_ours() -> float:
    """Steps/s of the batched module API (`forward_many`): per-step values and
    state accumulation for a CHUNK of steps in one `lax.scan` dispatch + one
    sync, amortizing the post-D2H round trip across the chunk."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(MANY_STEPS, BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, (MANY_STEPS, BATCH)))
    jax.block_until_ready((p, t))
    metric = Accuracy()
    metric.forward_many(p, t)  # eager-validated first chunk
    metric.forward_many(p, t)  # compiles the scan program
    jax.block_until_ready(metric.correct)
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        vals = metric.forward_many(p, t)
        jax.block_until_ready(vals)
        best = min(best, time.perf_counter() - start)
    return MANY_STEPS / best


def bench_overhead_deferred_ours() -> tuple:
    """Steps/s of the UNMODIFIED eager module API with deferred micro-batched
    dispatch on (the default): per-step `metric(preds, target)` calls enqueue
    and flush as stacked `lax.scan` programs at the queue threshold — the
    loop keeps the reference call shape and pays ~one dispatch per
    `METRICS_TPU_DEFER_MAX` steps instead of one per step. The trailing
    `block_until_ready` on the metric state is the observation that forces
    the final flush, so the measurement includes every flush the loop
    incurs."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import engine
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    metric = Accuracy()
    # warmup mirrors the timed protocol exactly: licenses the signature and
    # compiles the flush scan programs for every power-of-two bucket the
    # steady-state loop hits (threshold flushes + the final ragged flush)
    metric(p, t)
    for _ in range(OVERHEAD_STEPS):
        metric(p, t)
    jax.block_until_ready(metric.correct)
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        jax.block_until_ready(metric.correct)  # observation: final flush
        best = min(best, time.perf_counter() - start)
    # per-STEP latency distribution: most steps are a host-side enqueue
    # (µs), every METRICS_TPU_DEFER_MAX-th step pays the flush dispatch —
    # the bimodal shape is exactly what p50-vs-p99 makes visible
    lat = _latency_percentiles(lambda: metric(p, t), OVERHEAD_STEPS)
    jax.block_until_ready(metric.correct)
    return OVERHEAD_STEPS / best, lat


def bench_fault_overhead() -> dict:
    """Cost of the failure-domain instrumentation (ops/faults.py) on the hot
    deferred eager-API path: the same loop as `deferred_per_step` timed with
    injection DISARMED (production steady state — every site check is one
    module-attribute read) and ARMED with a never-firing plan (worst case
    short of an actual fault). Pins that fault classification, ladder
    bookkeeping and the injection sites add no measurable per-step cost."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import engine, faults
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))

    def loop_steps_per_s() -> float:
        metric = Accuracy()
        metric(p, t)
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        jax.block_until_ready(metric.correct)
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(OVERHEAD_STEPS):
                metric(p, t)
            jax.block_until_ready(metric.correct)  # observation: final flush
            best = min(best, time.perf_counter() - start)
        return OVERHEAD_STEPS / best

    disarmed = loop_steps_per_s()
    # a zero-budget plan arms the checks without ever firing: every site pays
    # its full lookup path, the worst steady-state cost the hook can add
    with faults.inject_faults("bench-never-fires", count=0):
        armed = loop_steps_per_s()
    return {"disarmed_steps_per_s": disarmed, "armed_steps_per_s": armed}


def bench_telemetry_overhead() -> dict:
    """Cost of the telemetry flight recorder (ops/telemetry.py) on the hot
    deferred eager-API path: the same loop as `deferred_per_step` timed with
    the span recorder DISARMED (one module-attribute read per site, zero
    allocation) and ARMED (default: one tuple append into the bounded ring
    per span — enqueue instants, flush/dispatch/compile slices). Pins the
    ISSUE-7 acceptance contract: disarmed ≈ baseline, armed overhead < 5%
    on the hot deferred loop."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import engine, telemetry
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))

    def loop_steps_per_s() -> float:
        metric = Accuracy()
        metric(p, t)
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        jax.block_until_ready(metric.correct)
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(OVERHEAD_STEPS):
                metric(p, t)
            jax.block_until_ready(metric.correct)  # observation: final flush
            best = min(best, time.perf_counter() - start)
        return OVERHEAD_STEPS / best

    def loop_latency() -> dict:
        metric = Accuracy()
        metric(p, t)
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        jax.block_until_ready(metric.correct)
        lat = _latency_percentiles(lambda: metric(p, t), OVERHEAD_STEPS)
        jax.block_until_ready(metric.correct)
        return lat

    was_armed = telemetry.armed
    try:
        telemetry.set_telemetry(False)
        disarmed = loop_steps_per_s()
        disarmed_lat = loop_latency()
        # armed now includes the FULL-LIFETIME histogram path: every timed
        # span emit is additionally one bucket increment (plus the cached
        # SLO-limit check), so armed≈disarmed pins histogram-armed overhead
        telemetry.set_telemetry(True)
        armed = loop_steps_per_s()
        armed_lat = loop_latency()
    finally:
        telemetry.set_telemetry(was_armed)
    return {
        "disarmed_steps_per_s": disarmed,
        "armed_steps_per_s": armed,
        "disarmed_latency_ms": disarmed_lat,
        "armed_latency_ms": armed_lat,
    }


def bench_device_probe_overhead() -> dict:
    """Cost of the sampled device-time probes (ISSUE 12) on the hot deferred
    eager-API loop: telemetry armed in BOTH passes, probes disarmed
    (``METRICS_TPU_DEVICE_PROBE_EVERY`` unset — one cached-int compare per
    dispatch, nothing allocated) vs armed at ``EVERY=8`` (every 8th program
    dispatch blocks until the device finishes and lands its inclusive wall
    in the ``device-dispatch:<program>`` family). The disarmed rate must sit
    inside the existing telemetry armed≈disarmed envelope — probes off is
    the bench-pinned default."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy
    from metrics_tpu.ops import engine, telemetry
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    PROBE_EVERY = 8

    def loop_steps_per_s() -> float:
        metric = Accuracy()
        metric(p, t)
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        jax.block_until_ready(metric.correct)
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(OVERHEAD_STEPS):
                metric(p, t)
            jax.block_until_ready(metric.correct)
            best = min(best, time.perf_counter() - start)
        return OVERHEAD_STEPS / best

    was_armed = telemetry.armed
    try:
        telemetry.set_telemetry(True)
        engine.set_device_probe(0)
        disarmed = loop_steps_per_s()
        probes_before = engine.engine_stats()["device_probes"]
        engine.set_device_probe(PROBE_EVERY)
        armed = loop_steps_per_s()
        probes = engine.engine_stats()["device_probes"] - probes_before
    finally:
        engine.set_device_probe(None)  # back to the env-driven default (off)
        telemetry.set_telemetry(was_armed)
    return {
        "disarmed_steps_per_s": disarmed,
        "armed_steps_per_s": armed,
        "probe_every": PROBE_EVERY,
        "device_probes": int(probes),
    }


def bench_sync_per_call() -> dict:
    """Whole-suite sync round-trip cost: coalesced vs per-state protocol.

    A 4-metric multi-state ``MetricCollection`` (8 array states total) runs
    ``sync``/``unsync`` cycles with the simulated-distributed hook (the same
    single-process protocol surface the dryrun certifies). Coalesced: ONE
    packed payload collective slot + one donated unpack program per sync.
    Per-state (``METRICS_TPU_SYNC_COALESCE=0``): one shape + one payload slot
    and one gather per state — 2·M·S protocol round trips. On the tunneled
    backend each blocking collective costs ~sync_roundtrip_ms (BENCH_r05), so
    collectives_per_sync IS the cost model; both syncs/s loops are reported
    for the local-dispatch floor comparison."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanAbsoluteError, MeanMetric, MeanSquaredError, MetricCollection
    from metrics_tpu.ops import engine

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    dist_on = lambda: True  # noqa: E731
    n_syncs = max(3, STEPS // 5)

    def loop(coalesce: bool) -> dict:
        os.environ["METRICS_TPU_SYNC_COALESCE"] = "1" if coalesce else "0"
        try:
            coll = MetricCollection(
                {
                    "mean": MeanMetric(),
                    "mse": MeanSquaredError(),
                    "mae": MeanAbsoluteError(),
                    "acc": Accuracy(),
                }
            )
            coll.update(p, t)
            # warmup compiles the pack/unpack (or per-state apply) programs
            coll.sync(distributed_available=dist_on)
            coll.unsync()
            from metrics_tpu.ops import perf as _perf
            from metrics_tpu.ops import telemetry as _telemetry

            s0 = engine.engine_stats()
            lat0 = _telemetry.latency_stats()
            best = float("inf")
            for _ in range(TRIALS):
                start = time.perf_counter()
                for _ in range(n_syncs):
                    coll.sync(distributed_available=dist_on)
                    coll.unsync()
                jax.block_until_ready(coll["mean"].value)
                best = min(best, time.perf_counter() - start)
            s1 = engine.engine_stats()
            per_sync = (
                s1["sync_shape_collectives"]
                + s1["sync_payload_collectives"]
                - s0["sync_shape_collectives"]
                - s0["sync_payload_collectives"]
            ) / (n_syncs * TRIALS)
            # sync-phase attribution columns (ISSUE 12): the per-phase wall
            # this row's cycles spent, the bytes that crossed the (simulated)
            # wire and the effective bandwidth — the decomposition
            # sweep_regress --explain consumes round over round
            phases = _perf.phase_columns(lat0, _telemetry.latency_stats())
            wire_ms = phases.get("wire", 0.0)
            bytes_gathered = s1["sync_bytes_gathered"] - s0["sync_bytes_gathered"]
            sync_phases = {
                k: v for k, v in phases.items()
                if k in ("pack", "serialize", "wire", "unpack", "orchestrate")
            }
            bound = (
                max(sync_phases, key=lambda k: sync_phases[k]) + "-bound"
                if sync_phases
                else "untelemetered"
            )

            def _cycle():
                coll.sync(distributed_available=dist_on)
                coll.unsync()

            lat = _latency_percentiles(_cycle, n_syncs)
            jax.block_until_ready(coll["mean"].value)
            return {
                "syncs_per_s": n_syncs / best,
                "collectives_per_sync": per_sync,
                "latency": lat,
                "phases_ms": phases,
                "achieved_gbps": (
                    (bytes_gathered / (wire_ms / 1000.0)) / 1e9 if wire_ms > 0 else 0.0
                ),
                "bound": bound,
            }
        finally:
            os.environ.pop("METRICS_TPU_SYNC_COALESCE", None)

    coalesced = loop(True)
    per_state = loop(False)
    return {
        "coalesced_syncs_per_s": coalesced["syncs_per_s"],
        "coalesced_collectives_per_sync": coalesced["collectives_per_sync"],
        "coalesced_latency_ms": coalesced["latency"],
        "coalesced_phases_ms": coalesced["phases_ms"],
        "achieved_gbps": coalesced["achieved_gbps"],
        "bound": coalesced["bound"],
        "per_state_syncs_per_s": per_state["syncs_per_s"],
        "per_state_collectives_per_sync": per_state["collectives_per_sync"],
        "per_state_latency_ms": per_state["latency"],
    }


def bench_sync_deadline_overhead() -> dict:
    """Healthy-path cost of the sync watchdog (ISSUE 6): the same
    suite sync/unsync loop as ``sync_per_call`` timed with
    ``METRICS_TPU_SYNC_DEADLINE_MS`` UNSET (production default — the
    pre-deadline direct call, zero threads) and ARMED with a generous
    deadline that never fires (each collective rides a watchdog-monitored
    thread). armed≈disarmed pins the acceptance contract: with the knob
    unset, behavior and hot-path cost are unchanged."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanMetric, MetricCollection

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    dist_on = lambda: True  # noqa: E731
    n_syncs = max(3, STEPS // 5)

    def loop(deadline_ms, degraded=None) -> float:
        if deadline_ms is None:
            os.environ.pop("METRICS_TPU_SYNC_DEADLINE_MS", None)
        else:
            os.environ["METRICS_TPU_SYNC_DEADLINE_MS"] = str(deadline_ms)
        if degraded is None:
            os.environ.pop("METRICS_TPU_SYNC_DEGRADED", None)
        else:
            os.environ["METRICS_TPU_SYNC_DEGRADED"] = degraded
        try:
            coll = MetricCollection({"mean": MeanMetric(), "acc": Accuracy()})
            coll.update(p, t)
            coll.sync(distributed_available=dist_on)
            coll.unsync()
            best = float("inf")
            for _ in range(TRIALS):
                start = time.perf_counter()
                for _ in range(n_syncs):
                    coll.sync(distributed_available=dist_on)
                    coll.unsync()
                jax.block_until_ready(coll["mean"].value)
                best = min(best, time.perf_counter() - start)
            return n_syncs / best
        finally:
            os.environ.pop("METRICS_TPU_SYNC_DEADLINE_MS", None)
            os.environ.pop("METRICS_TPU_SYNC_DEGRADED", None)

    disarmed = loop(None)
    armed = loop(60_000)
    # ISSUE 8: deadline + quorum tier + epoch fence all armed on a HEALTHY
    # transport — every collective additionally captures/checks its epoch
    # fence and folds success into the membership registry
    membership_armed = loop(60_000, degraded="quorum")
    return {
        "disarmed_syncs_per_s": disarmed,
        "armed_syncs_per_s": armed,
        "membership_armed_syncs_per_s": membership_armed,
    }


def bench_async_sync_overlap() -> dict:
    """``async_sync_overlap``: does the async lane actually hide the wire?

    A 4-metric suite cycles (K deferred updates + one sync) against a
    SIMULATED slow transport (the payload all-gather sleeps a fixed
    ``simulated_rtt_ms`` — the BENCH_r03–r05 tunnel regime, where one
    blocking collective costs ~69 ms of pure latency). Blocking: sync, then
    the updates (wire on the critical path). Async: ``sync_async`` first,
    the SAME updates run while the wire flies, ``compute()`` forces. The
    steps/s ratio is the overlap win, and ``wire_hidden_fraction`` (from
    ``perf_report``'s overlapped-wire evidence) is the proof the wall moved
    off the critical path — the acceptance gate ``tools/sweep_regress.py``
    tracks round over round."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanAbsoluteError, MeanMetric, MeanSquaredError, MetricCollection, perf_report
    from metrics_tpu.ops import telemetry
    from metrics_tpu.parallel import bucketing

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    dist_on = lambda: True  # noqa: E731
    rtt_s = 0.02 if not SMOKE else 0.005
    n_cycles = max(2, STEPS // 10)

    def make():
        c = MetricCollection(
            {
                "mean": MeanMetric(),
                "mse": MeanSquaredError(),
                "mae": MeanAbsoluteError(),
                "acc": Accuracy(),
            }
        )
        c.update(p, t)
        return c

    # size the overlap window: enough FORCED update work per cycle to cover
    # ~2x the simulated RTT (jax dispatches are async — without the
    # block_until_ready inside the cycle the host would finish its updates
    # in microseconds and the force would wait out the whole wire anyway)
    probe = make()
    for _ in range(4):
        probe.update(p, t)
    jax.block_until_ready(probe["mean"].value)
    # update wall is SUBLINEAR in call count when the deferral layer is on
    # (calls enqueue, one stacked flush materializes them), so size the
    # window by doubling until the measured forced wall covers ~2x the RTT
    updates_per_cycle = 8
    while updates_per_cycle < 512:
        wall = float("inf")
        for _ in range(2):  # best-of-2: the first pass may compile the chunk
            start = time.perf_counter()
            for _ in range(updates_per_cycle):
                probe.update(p, t)
            jax.block_until_ready(probe["mean"].value)
            wall = min(wall, time.perf_counter() - start)
        if wall >= 2 * rtt_s:
            break
        updates_per_cycle *= 2

    saved_payload = bucketing._payload_allgather

    def slow_payload(x):
        time.sleep(rtt_s)
        return saved_payload(x)

    was_armed = telemetry.armed
    telemetry.set_telemetry(True)
    try:
        bucketing._payload_allgather = slow_payload

        def cycle_blocking(coll):
            coll.sync(distributed_available=dist_on)
            coll.unsync()
            for _ in range(updates_per_cycle):
                coll.update(p, t)
            jax.block_until_ready(coll["mean"].value)

        def cycle_async(coll):
            fut = coll.sync_async(distributed_available=dist_on)
            for _ in range(updates_per_cycle):
                coll.update(p, t)
            # the cycle's compute lands WHILE the wire flies
            jax.block_until_ready(coll["mean"].value)
            fut.wait()
            coll.unsync()

        def run(cycle):
            coll = make()
            cycle(coll)  # warmup: programs compile, manifest caches
            best = float("inf")
            for _ in range(TRIALS):
                start = time.perf_counter()
                for _ in range(n_cycles):
                    cycle(coll)
                jax.block_until_ready(coll["mean"].value)
                best = min(best, time.perf_counter() - start)
            return n_cycles * updates_per_cycle / best

        blocking = run(cycle_blocking)
        telemetry.clear_spans()
        overlapped = run(cycle_async)
        wire = perf_report()["sync"]["wire"]
    finally:
        bucketing._payload_allgather = saved_payload
        telemetry.set_telemetry(was_armed)
    return {
        "blocking_steps_per_s": blocking,
        "async_steps_per_s": overlapped,
        "overlap_speedup": overlapped / blocking if blocking > 0 else 0.0,
        "wire_hidden_fraction": float(wire["wire_hidden_fraction"]),
        "overlapped_wire_ms": float(wire["overlapped_wire_s"]) * 1e3,
        "forced_wait_ms": float(wire["forced_wait_s"]) * 1e3,
        "simulated_rtt_ms": rtt_s * 1e3,
        "updates_per_cycle": updates_per_cycle,
    }


def bench_sync_quant_payload() -> dict:
    """``sync_quant_payload``: bytes on the wire per suite sync, f32 vs the
    quantized lanes (``METRICS_TPU_SYNC_QUANT=bf16|int8``). The suite mixes
    float vector states (binned curves — the lossy lane's target) with
    integer count states (routed around the encoder, the exactness
    carve-out), so the reduction ratio reflects a real mixed suite rather
    than a best case."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, BinnedPrecisionRecallCurve, MetricCollection
    from metrics_tpu.ops import engine

    rng = np.random.RandomState(0)
    n_cls = 8
    probs = rng.rand(256, n_cls).astype(np.float32)
    probs /= probs.sum(axis=1, keepdims=True)
    labels = rng.randint(0, n_cls, size=256)
    dist_on = lambda: True  # noqa: E731

    def bytes_for(tier) -> dict:
        if tier is None:
            os.environ.pop("METRICS_TPU_SYNC_QUANT", None)
        else:
            os.environ["METRICS_TPU_SYNC_QUANT"] = tier
        try:
            coll = MetricCollection(
                {
                    "curve": BinnedPrecisionRecallCurve(num_classes=n_cls, thresholds=64),
                    "acc": Accuracy(num_classes=n_cls),
                }
            )
            coll.update(jnp.asarray(probs), jnp.asarray(labels))
            s0 = engine.engine_stats()
            coll.sync(distributed_available=dist_on)
            coll.unsync()
            s1 = engine.engine_stats()
            return {
                "bytes": s1["sync_bytes_gathered"] - s0["sync_bytes_gathered"],
                "exact_states": s1["sync_quant_exact_states"] - s0["sync_quant_exact_states"],
                "lossy_states": s1["sync_quant_lossy_states"] - s0["sync_quant_lossy_states"],
            }
        finally:
            os.environ.pop("METRICS_TPU_SYNC_QUANT", None)

    f32 = bytes_for(None)
    bf16 = bytes_for("bf16")
    int8 = bytes_for("int8")
    return {
        "f32_bytes_per_sync": f32["bytes"],
        "bf16_bytes_per_sync": bf16["bytes"],
        "int8_bytes_per_sync": int8["bytes"],
        "bf16_reduction": f32["bytes"] / bf16["bytes"] if bf16["bytes"] else 0.0,
        "int8_reduction": f32["bytes"] / int8["bytes"] if int8["bytes"] else 0.0,
        "quant_exact_states": int8["exact_states"],
        "quant_lossy_states": int8["lossy_states"],
    }


def bench_journal_write() -> dict:
    """``journal_write_per_snapshot``: wall-clock cost of one crash-consistent
    suite snapshot (pack program + CRC + atomic write + ring rotation) on a
    4-metric multi-state suite — the cadence budget for
    ``MetricCollection.journal(path, every_n)``."""
    import tempfile

    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanAbsoluteError, MeanMetric, MeanSquaredError, MetricCollection

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    coll = MetricCollection(
        {
            "mean": MeanMetric(),
            "mse": MeanSquaredError(),
            "mae": MeanAbsoluteError(),
            "acc": Accuracy(),
        }
    )
    coll.update(p, t)
    d = tempfile.mkdtemp(prefix="mt-bench-journal-")
    path = os.path.join(d, "suite.journal")
    nbytes = coll.save_state(path)  # warmup: compiles the pack program
    n_snaps = max(3, STEPS // 5)
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(n_snaps):
            coll.save_state(path)
        best = min(best, time.perf_counter() - start)
    lat = _latency_percentiles(lambda: coll.save_state(path), n_snaps)
    return {
        "snapshots_per_s": n_snaps / best,
        "ms_per_snapshot": 1000.0 * best / n_snaps,
        "record_bytes": nbytes,
        "latency_ms": lat,
    }


def bench_fleet_snapshot() -> dict:
    """``fleet_snapshot_overhead``: cost of one fleet snapshot merge in a
    world of size 1 — the production single-replica default. The contract
    (ISSUE 9): zero collectives issued (the local plane serves directly;
    counter-asserted via the protocol-slot audit). armed vs disarmed
    isolates only the per-call span emit — disarming does NOT drop the
    already-recorded ring, so both loops pay the same span-ring
    phase-stats reduction inside telemetry.snapshot()."""
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanMetric, MetricCollection
    from metrics_tpu.ops import engine, fleetobs, telemetry

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))
    coll = MetricCollection({"mean": MeanMetric(), "acc": Accuracy()})
    coll.update(p, t)
    # one simulated-world sync so the span ring carries sync-phase material
    coll.sync(distributed_available=lambda: True)
    coll.unsync()
    n_snaps = max(5, STEPS // 5)
    calls = {"n": 0}

    def loop() -> float:
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(n_snaps):
                fleetobs.fleet_snapshot()
            calls["n"] += n_snaps
            best = min(best, time.perf_counter() - start)
        return n_snaps / best

    was_armed = telemetry.armed
    s0 = engine.engine_stats()["sync_collectives_issued"]
    try:
        telemetry.set_telemetry(True)
        armed = loop()
        lat = _latency_percentiles(fleetobs.fleet_snapshot, n_snaps)
        calls["n"] += n_snaps
        telemetry.set_telemetry(False)
        disarmed = loop()
    finally:
        telemetry.set_telemetry(was_armed)
    collectives = engine.engine_stats()["sync_collectives_issued"] - s0
    return {
        "armed_snapshots_per_s": armed,
        "disarmed_snapshots_per_s": disarmed,
        "collectives_per_snapshot": collectives / max(1, calls["n"]),
        "latency_ms": lat,
    }


def bench_window_close() -> dict:
    """``window_close``: wall-clock cost of one window close on a 4-metric
    suite — agree the close id, merge the stride state, pack it into a ring
    slot — the cadence budget for ``Windowed(suite, window, stride)``. The
    stride updates stage OUTSIDE the timer: the row prices the close itself.
    Two collective budgets ride along, counted rather than timed: a
    world-size-1 close issues ZERO collectives, and a simulated 3-rank close
    issues exactly ONE payload collective (``collectives_per_close_live``) —
    the ceiling ``tools/sweep_regress.py`` gates."""
    import jax.numpy as jnp

    from metrics_tpu import (
        Accuracy,
        MeanAbsoluteError,
        MeanMetric,
        MeanSquaredError,
        MetricCollection,
        Windowed,
    )
    from metrics_tpu.ops import engine
    from metrics_tpu.parallel import bucketing
    from metrics_tpu.parallel import sync as psync

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))

    def suite() -> MetricCollection:
        return MetricCollection(
            {
                "mean": MeanMetric(),
                "mse": MeanSquaredError(),
                "mae": MeanAbsoluteError(),
                "acc": Accuracy(),
            }
        )

    win = Windowed(suite(), window=8, stride=2, name="bench-window")

    def stage() -> None:
        win.base.update(p, t)
        win.base.update(p, t)

    stage()
    win.close_window()  # warmup: compiles the pack program
    record_bytes = len(win._ring[-1][1])
    n_closes = max(3, STEPS // 5)
    c0 = engine.engine_stats()["sync_collectives_issued"]
    best = float("inf")
    for _ in range(TRIALS):
        elapsed = 0.0
        for _ in range(n_closes):
            stage()
            start = time.perf_counter()
            win.close_window()
            elapsed += time.perf_counter() - start
        best = min(best, elapsed)
    lat = _latency_percentiles(win.close_window, n_closes, setup=stage)
    n_local = TRIALS * n_closes + n_closes
    collectives_local = (engine.engine_stats()["sync_collectives_issued"] - c0) / n_local

    # the live budget: a fake 3-rank world over stacked local transports —
    # counted, not timed (a stacked transport has no wire worth measuring)
    saved_payload = bucketing._payload_allgather
    saved_host = bucketing._host_allgather
    psync.reset_membership()
    try:
        psync.set_expected_world(3)
        bucketing._host_allgather = lambda vec: np.stack([np.asarray(vec)] * 3)
        bucketing._payload_allgather = lambda packed: jnp.stack([packed] * 3)
        fwin = Windowed(suite(), window=4, stride=2, name="bench-window-live")
        n_live = 4
        p0 = engine.engine_stats()["sync_payload_collectives"]
        for _ in range(n_live):
            fwin.base.update(p, t)
            fwin.base.update(p, t)
            fwin.close_window(distributed_available=lambda: True)
        live = (engine.engine_stats()["sync_payload_collectives"] - p0) / n_live
    finally:
        bucketing._payload_allgather = saved_payload
        bucketing._host_allgather = saved_host
        psync.reset_membership()
    return {
        "closes_per_s": n_closes / best,
        "ms_per_close": 1000.0 * best / n_closes,
        "record_bytes": record_bytes,
        "collectives_per_close": collectives_local,
        "collectives_per_close_live": live,
        "latency_ms": lat,
    }


def bench_drift_report() -> dict:
    """``drift_report``: cost of one PSI/KS drift computation over two
    4096-sample raw-state vectors (shared linear binning through
    ``ops/histogram.py``) — the scrape-cadence budget for
    ``Windowed.drift_report()`` and the module-level ``drift_report``."""
    from metrics_tpu import drift_report

    rng = np.random.RandomState(15)
    cur = rng.normal(0.5, 1.2, 4096).astype(np.float32)
    ref = rng.normal(0.0, 1.0, 4096).astype(np.float32)
    report = drift_report(cur, ref)  # warmup: compiles the fused bincount
    n_reports = max(5, STEPS // 5)
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(n_reports):
            drift_report(cur, ref)
        best = min(best, time.perf_counter() - start)
    lat = _latency_percentiles(lambda: drift_report(cur, ref), n_reports)
    return {
        "reports_per_s": n_reports / best,
        "ms_per_report": 1000.0 * best / n_reports,
        "sample_size": 4096,
        "psi": float(report["psi"]),
        "ks": float(report["ks"]),
        "latency_ms": lat,
    }


def bench_arena_suites() -> dict:
    """``arena_suites``: N concurrent 2-metric suites as ONE ``MetricArena``
    (ISSUE 17) vs the per-instance Python loop. Three numbers matter per
    tenant tier: ``suites_per_s`` (tenant-updates the vmapped donated
    program retires per second), the per-instance loop's rate measured on a
    sample of real module instances (linear extrapolation — each instance
    pays its own dispatch), and their ratio (``vs_loop`` — the ≥10x floor
    ``tools/sweep_regress.py`` gates at the 100k tier). The 1M tier proves
    the slab-bucketed shape discipline: its ``builds`` column counts every
    program the engine traced for the whole tier — bounded by the distinct
    slab buckets and pow2 chunk sizes touched, NOT by N. ``retraces_per_add``
    pins the lifecycle cost: one-at-a-time adds across slab boundaries
    retrace only when a new capacity bucket appears. ``slab_record_bytes``
    prices one CRC-framed per-slab journal record."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanMetric, MetricCollection
    from metrics_tpu.arena import MetricArena
    from metrics_tpu.ops import engine

    def make_suite():
        return MetricCollection({"acc": Accuracy(num_classes=2), "mean": MeanMetric()})

    rng = np.random.RandomState(17)
    per_tenant = 8  # samples each tenant sees per step
    tiers = (64, 256, 1024) if SMOKE else (1_000, 100_000, 1_000_000)
    slab = 64 if SMOKE else 1024
    loop_sample = 32 if SMOKE else 256
    out: dict = {"tiers": {}, "slab": slab, "per_tenant_batch": per_tenant}

    # per-instance loop rate, measured once on a sample of real module
    # instances and extrapolated linearly (the loop IS linear in N: each
    # instance pays its own dispatch) — timing 1M python dispatches would
    # burn minutes to state the obvious
    preds_s = jnp.asarray(rng.randint(0, 2, (loop_sample, per_tenant)).astype(np.int32))
    target_s = jnp.asarray(rng.randint(0, 2, (loop_sample, per_tenant)).astype(np.int32))
    instances = [make_suite() for _ in range(loop_sample)]
    for i, m in enumerate(instances):  # warmup: compiles the member programs
        m.update(preds_s[i], target_s[i])
    loop_steps = 1 if SMOKE else 3
    best_loop = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(loop_steps):
            for i, m in enumerate(instances):
                m.update(preds_s[i], target_s[i])
        for m in instances:
            for node in m.values(copy_state=False):
                jax.block_until_ready(jax.tree.leaves(node.metric_state))
        best_loop = min(best_loop, time.perf_counter() - start)
    loop_suites_per_s = loop_sample * loop_steps / best_loop if best_loop > 0 else 0.0
    out["loop_suites_per_s"] = round(loop_suites_per_s, 1)
    out["loop_sample"] = loop_sample

    for n in tiers:
        arena = MetricArena(make_suite(), capacity=n, slab=slab, name=f"bench{n}")
        ids = arena.add(n)
        preds = jnp.asarray(rng.randint(0, 2, (n, per_tenant)).astype(np.int32))
        target = jnp.asarray(rng.randint(0, 2, (n, per_tenant)).astype(np.int32))
        b0 = engine.engine_stats()["builds"]
        arena.update(ids, preds, target)  # warmup: traces the chunk programs
        jax.block_until_ready(jax.tree.leaves(arena._stacked))
        steps = max(1, (STEPS // 5) if n >= 100_000 else STEPS // 2)
        best = float("inf")
        for _ in range(TRIALS):
            start = time.perf_counter()
            for _ in range(steps):
                arena.update(ids, preds, target)
            jax.block_until_ready(jax.tree.leaves(arena._stacked))
            best = min(best, time.perf_counter() - start)
        builds = engine.engine_stats()["builds"] - b0
        suites_per_s = n * steps / best if best > 0 else 0.0
        out["tiers"][str(n)] = {
            "suites_per_s": round(suites_per_s, 1),
            "vs_loop": round(suites_per_s / loop_suites_per_s, 2)
            if loop_suites_per_s > 0
            else 0.0,
            "builds": int(builds),
            "ms_per_step": round(1000.0 * best / steps, 3),
        }
        del arena, preds, target

    # lifecycle: one-at-a-time adds across slab boundaries, updating only the
    # new tenant — the builds delta counts exactly one chunk-1 program per
    # NEW capacity bucket (zero retraces inside a bucket)
    small_slab = 8 if SMOKE else 64
    adds = small_slab * 8  # crosses three slab-bucket boundaries
    arena = MetricArena(make_suite(), capacity=small_slab, slab=small_slab, name="bench_life")
    one_p = jnp.asarray(rng.randint(0, 2, (1, per_tenant)).astype(np.int32))
    one_t = jnp.asarray(rng.randint(0, 2, (1, per_tenant)).astype(np.int32))
    b0 = engine.engine_stats()["builds"]
    for _ in range(adds):
        (tid,) = arena.add(1)
        arena.update([tid], one_p, one_t)
    lifecycle_builds = engine.engine_stats()["builds"] - b0
    out["retraces_per_add"] = round(lifecycle_builds / adds, 4)
    out["lifecycle_builds"] = int(lifecycle_builds)
    out["lifecycle_adds"] = adds
    out["lifecycle_buckets"] = 4  # small_slab*1, *2, *4, *8

    # slab-record bytes: one CRC-framed record per slab (pack_raw_record)
    with tempfile.TemporaryDirectory() as d:
        total = arena.save(os.path.join(d, "arena.j"))
    out["slab_record_bytes"] = int(total // arena.slabs)
    out["slabs"] = arena.slabs
    return out


def bench_ingest_gateway() -> dict:
    """``ingest_gateway``: the admission-controlled front door (ISSUE 19).
    Three numbers: sustained admitted rows/s through ``offer()`` + ``flush()``
    into a ``MetricArena`` (columnar staging + occurrence-split dispatch
    riding the arena's pow2-chunked vmapped program), per-offer latency
    percentiles on the pinned-schema fast path, and the shed fraction at
    exactly 2x overload against a bounded row watermark — with the
    settlement accounting identity (`offered == admitted + coalesced + shed
    + quarantined`) checked exactly after the drain.
    ``tools/sweep_regress.py`` gates the overload row at
    ``--ingest-shed-ceiling`` (a gateway that sheds MORE than the overload
    excess is throwing away admissible load) and fails any run where the
    identity broke."""
    import jax

    from metrics_tpu.aggregation import MeanMetric
    from metrics_tpu.arena import MetricArena
    from metrics_tpu.ingest import IngestGateway
    from metrics_tpu.ops import engine

    engine.reset_stats()
    rng = np.random.RandomState(19)
    tenants = 64 if SMOKE else 256
    rows = tenants  # one row per tenant per payload
    payloads = 8 if SMOKE else 64
    arena = MetricArena(MeanMetric(), capacity=tenants, slab=min(64, tenants), name="bench_ingest")
    ids = np.asarray(arena.add(tenants))
    gw = IngestGateway(
        arena, name="bench_ingest", auto_flush=True,
        max_rows=rows * payloads * 2, flush_rows=rows * 8,
    )
    x = rng.rand(rows, 4).astype(np.float32)
    gw.offer(x, tenant_ids=ids)
    gw.flush()  # warmup: pins the schema + compiles the arena chunk program
    jax.block_until_ready(jax.tree.leaves(arena._stacked))
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(payloads):
            gw.offer(x, tenant_ids=ids)
        gw.flush()
        jax.block_until_ready(jax.tree.leaves(arena._stacked))
        best = min(best, time.perf_counter() - start)
    admitted_per_s = rows * payloads / best if best > 0 else 0.0
    lat = _latency_percentiles(lambda: gw.offer(x, tenant_ids=ids), payloads)
    gw.flush()
    gw.close()

    # 2x overload: a bounded gateway fed exactly twice its row watermark
    # with no consumer until the burst ends — the shed fraction should sit
    # at the overload excess (~0.5), never above the regression ceiling
    engine.reset_stats()
    over = IngestGateway(
        arena, name="bench_ingest_2x", auto_flush=False, max_rows=rows * payloads,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the shed warning is the point
        for _ in range(payloads * 2):
            over.offer(x, tenant_ids=ids)
        over.flush()
        s = engine.engine_stats()
        shed_fraction = s["ingest_shed_rows"] / max(1, s["ingest_offered_rows"])
        exact = s["ingest_offered_rows"] == (
            s["ingest_admitted_rows"] + s["ingest_coalesced_rows"]
            + s["ingest_shed_rows"] + s["ingest_quarantined_rows"]
        )
        over.close()
    return {
        "admitted_updates_per_s": round(admitted_per_s, 1),
        "latency_ms": lat,
        "shed_fraction_2x": round(float(shed_fraction), 4),
        "accounting_exact": bool(exact),
        "tenants": tenants,
        "payload_rows": rows,
        "payloads_per_flush": payloads,
    }


def bench_cold_start() -> dict:
    """``cold_start``: fleet replica replacement (ISSUE 18) — first-result
    latency and compiles-per-boot for a fresh engine, cold (empty store)
    vs warmed (persistent progcache populated, ``precompile()`` on boot).
    The cold boot traces and compiles every update/flush/compute program
    and exports each into the on-disk store; the warmed boot replays the
    identical traffic with the store attached and must serve EVERY program
    from disk: ``warm_boot_compiles`` is the number it compiled anyway —
    ``tools/sweep_regress.py`` gates it at ``--warm-boot-compile-ceiling``
    (default 0.0). ``replacement_wall_ms`` prices the whole warmed boot
    (construct + precompile + first traffic to a synced result) — the wall
    a rolling restart pays per replica. In-process boots (fresh engine,
    fresh module instances, fresh jit twins) isolate compile-vs-load; the
    true two-process certification lives in ``make dryrun``
    (``cold_start_certification``)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanMetric, MetricCollection
    from metrics_tpu.ops import engine, progcache
    from metrics_tpu.utils.checks import set_validation_mode

    set_validation_mode("first")
    rng = np.random.RandomState(23)
    batches = [
        (
            jnp.asarray(rng.randint(0, 2, (64,)).astype(np.int32)),
            jnp.asarray(rng.randint(0, 2, (64,)).astype(np.int32)),
        )
        for _ in range(8)
    ]

    def make_suite():
        return MetricCollection(
            {"acc": Accuracy(num_classes=2), "mean": MeanMetric()}
        )

    def boot(warmed: bool) -> dict:
        engine.reset_engine()
        engine.reset_stats(reset_warnings=True)
        t_boot = time.perf_counter()
        suite = make_suite()
        if warmed:
            sds = jax.ShapeDtypeStruct((64,), jnp.int32)
            suite.precompile(sds, sds, defer_chunks=4, forward=False)
        t0 = time.perf_counter()
        suite.update(*batches[0])
        suite.update(*batches[1])
        first = suite.compute()
        jax.block_until_ready(jax.tree.leaves(first))
        first_result_ms = (time.perf_counter() - t0) * 1e3
        # the remaining traffic walks the same pow2 flush ladder the warmed
        # boot's precompile(defer_chunks=4) drives — chunk sets {1, 2, 4} —
        # so the cold boot stores exactly the programs a warmed boot needs
        for i, stop in ((2, 4), (4, 8)):
            for b in batches[i:stop]:
                suite.update(*b)
            final = suite.compute()
        jax.block_until_ready(jax.tree.leaves(final))
        wall_ms = (time.perf_counter() - t_boot) * 1e3
        stats = progcache.progcache_stats()
        return {
            "first_result_ms": first_result_ms,
            "wall_ms": wall_ms,
            "compiles": int(engine.program_summary()["compiles"]),
            "hits": int(stats["progcache_hits"]),
            "stores": int(stats["progcache_stores"]),
            "bytes": int(stats["progcache_bytes_stored"]),
        }

    store = tempfile.mkdtemp(prefix="metrics_tpu_coldstart_")
    try:
        progcache.configure(reset=True)
        progcache.configure(enabled=True, cache_dir=store)
        cold = boot(warmed=False)
        warm = boot(warmed=True)
    finally:
        progcache.configure(reset=True)
        engine.reset_engine()
        engine.reset_stats(reset_warnings=True)
        shutil.rmtree(store, ignore_errors=True)
        try:  # the store pointed JAX's own cache under it; point it back
            jax.config.update(
                "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
            )
        except Exception:  # noqa: BLE001 — older jax without the knob
            pass

    speedup = (
        round(cold["first_result_ms"] / warm["first_result_ms"], 2)
        if warm["first_result_ms"] > 0
        else 0.0
    )
    return {
        "cold_first_result_ms": round(cold["first_result_ms"], 3),
        "warm_first_result_ms": round(warm["first_result_ms"], 3),
        "first_result_speedup": speedup,
        "cold_compiles": cold["compiles"],
        "cold_stores": cold["stores"],
        "store_bytes": cold["bytes"],
        "warm_boot_compiles": warm["compiles"],
        "warm_hits": warm["hits"],
        "replacement_wall_ms": round(warm["wall_ms"], 3),
        "cold_wall_ms": round(cold["wall_ms"], 3),
    }


def bench_kernel_attack() -> dict:
    """``kernel_attack``: the roofline-guided variant sweep over every
    registered heavy kernel (ISSUE 20). For each kernel the autotuner times
    every registered formulation through real ``Executable`` dispatch on a
    representative shape, checks each against the reference under its
    declared exactness contract, and installs the winner. The row family
    reports, per kernel: the reference (baseline) wall and utilization, the
    winner's wall and utilization, the name of the winning variant and the
    winner/baseline score ratio. ``kernel_min_winner_vs_baseline`` — the
    worst ratio across kernels — is what ``sweep_regress`` gates at
    ``--kernel-utilization-floor`` (default 1.0: the sweep may never install
    a variant that scores below the reference; a drop below 1.0 means the
    selection machinery itself broke)."""
    import jax.numpy as jnp

    from metrics_tpu.ops import autotune

    autotune.load_registrations()
    rng = np.random.RandomState(0)
    n = BATCH
    scores = jnp.asarray(rng.rand(n).astype(np.float32))
    labels = jnp.asarray((rng.rand(n) > 0.5).astype(np.int32))
    d = 32 if SMOKE else 256
    q, _ = np.linalg.qr(rng.randn(d, d))
    s1 = jnp.asarray(((q * np.linspace(0.1, 2.0, d)[None, :]) @ q.T).astype(np.float32))
    s2 = jnp.asarray(((q * np.linspace(2.0, 0.1, d)[None, :]) @ q.T).astype(np.float32))
    det = (rng.rand(128, 4) * 64).astype(np.float32)
    det[:, 2:] += det[:, :2]
    gt = (rng.rand(64, 4) * 64).astype(np.float32)
    gt[:, 2:] += gt[:, :2]
    cases = {
        "auroc_sort": (scores, labels),
        "ap_sort": (scores, labels),
        "bincount": (jnp.asarray(rng.randint(0, NUM_CLASSES, n), jnp.int32), NUM_CLASSES),
        "binned_counts": (
            jnp.asarray(rng.rand(max(n // 8, 64), 16).astype(np.float32)),
            jnp.asarray((rng.rand(max(n // 8, 64), 16) > 0.5).astype(np.float32)),
            jnp.asarray(rng.rand(100).astype(np.float32)),
        ),
        "fid_sqrtm": (s1, s2),
        "map_box_iou": (det, gt),
    }
    assert set(cases) == set(autotune.kernels()), (
        "bench_kernel_attack must cover every registered kernel family"
    )
    autotune.configure(enabled=True, reset=True)
    try:
        per_kernel = {}
        min_ratio = float("inf")
        t_sweep_all = time.perf_counter()
        for kernel, args in sorted(cases.items()):
            rep = autotune.sweep(kernel, args, trials=TRIALS)
            ref_row = next(r for r in rep["candidates"] if r["reference"])
            win_row = next(r for r in rep["candidates"] if r["variant"] == rep["winner"])
            ratio = (
                win_row["score"] / ref_row["score"] if ref_row["score"] > 0 else 0.0
            )
            min_ratio = min(min_ratio, ratio)
            per_kernel[kernel] = {
                "baseline": rep["reference"],
                "winner": rep["winner"],
                "baseline_ms": round(1000.0 * (ref_row["wall_s"] or 0.0), 4),
                "winner_ms": round(1000.0 * (win_row["wall_s"] or 0.0), 4),
                "baseline_utilization": round(
                    max(ref_row["compute_utilization"], ref_row["memory_utilization"]), 6
                ),
                "winner_utilization": round(
                    max(win_row["compute_utilization"], win_row["memory_utilization"]), 6
                ),
                "winner_vs_baseline": round(ratio, 3),
                "candidates": len(rep["candidates"]),
                "disqualified": rep["disqualified"],
            }
        sweep_wall_s = time.perf_counter() - t_sweep_all
        stats = autotune.autotune_stats()
        return {
            "kernels": per_kernel,
            "kernel_min_winner_vs_baseline": round(min_ratio, 3),
            "sweeps": stats["autotune_sweeps"],
            "candidates": stats["autotune_candidates"],
            "disqualified": stats["autotune_disqualified"],
            # the one-time cost a cold process pays for the whole attack —
            # a warm boot (persisted selection table) pays none of it
            "sweep_wall_ms": round(1000.0 * sweep_wall_s, 1),
            "sweeps_per_s": round(stats["autotune_sweeps"] / sweep_wall_s, 2)
            if sweep_wall_s > 0
            else 0.0,
        }
    finally:
        # the sweep must not leak an armed autotuner (or its installed
        # selections) into the rows that follow
        autotune.configure(enabled=False, reset=True)


def bench_ingraph_step() -> dict:
    """``ingraph_step``: the functional-core whole-suite step — ONE jitted,
    donated ``apply_update`` program over an epoch-stamped ``FuncState``
    tree, the in-graph replacement for the host sync plane
    (docs/performance.md "Zero host round trips"). Three numbers matter:
    steps/s for the suite step itself, ``host_collectives_per_step`` == 0
    (counter-asserted — the host sync protocol never runs), and the wire
    phase share == 0 of the measured wall (there is no host wire at all;
    the cross-device merge compiles into the step). ``sweep_regress`` gates
    the zero: an in-graph step that starts issuing host collectives is a
    regression, not a tuning choice."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanAbsoluteError, MeanMetric, MeanSquaredError, MetricCollection
    from metrics_tpu.ops import engine
    from metrics_tpu.ops import perf as _perf
    from metrics_tpu.ops import telemetry as _telemetry

    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(BATCH).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, BATCH))

    suite = MetricCollection(
        {
            "mean": MeanMetric(),
            "mse": MeanSquaredError(),
            "mae": MeanAbsoluteError(),
            "acc": Accuracy(),
        }
    )
    state = suite.init()
    step = jax.jit(lambda st, a, b: suite.apply_update(st, a, b), donate_argnums=0)
    state = step(state, p, t)  # warmup: compiles the whole-suite program
    jax.block_until_ready(state.states)

    n_steps = max(8, STEPS)
    s0 = engine.engine_stats()
    lat0 = _telemetry.latency_stats()
    best = float("inf")
    elapsed_total = 0.0
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(n_steps):
            state = step(state, p, t)
        jax.block_until_ready(state.states)
        took = time.perf_counter() - start
        elapsed_total += took
        best = min(best, took)
    s1 = engine.engine_stats()
    host_per_step = (
        s1["sync_collectives_issued"] - s0["sync_collectives_issued"]
    ) / (n_steps * TRIALS)
    phases = _perf.phase_columns(lat0, _telemetry.latency_stats())
    wire_ms = phases.get("wire", 0.0)
    wire_share = (
        wire_ms / (1000.0 * elapsed_total) if elapsed_total > 0 and wire_ms > 0 else 0.0
    )

    def _cycle():
        nonlocal state
        state = step(state, p, t)
        jax.block_until_ready(state.states)

    lat = _latency_percentiles(_cycle, n_steps)
    value = suite.apply_compute(state)  # world-size-1 in-graph compute
    jax.block_until_ready(value)
    return {
        "steps_per_s": (n_steps / best) if best > 0 else 0.0,
        "ms_per_step": 1000.0 * best / n_steps,
        "host_collectives_per_step": host_per_step,
        "wire_phase_ms": wire_ms,
        "wire_share": wire_share,
        "latency_ms": lat,
        "devices": len(jax.devices()),
    }


def bench_overhead_reference() -> float:
    tm = _reference()
    if tm is None:
        return 0.0
    import torch

    rng = np.random.RandomState(0)
    p = torch.tensor(rng.rand(BATCH).astype(np.float32))
    t = torch.tensor(rng.randint(0, 2, BATCH))
    metric = tm.Accuracy()
    for _ in range(3):
        metric(p, t)
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(OVERHEAD_STEPS):
            metric(p, t)
        best = min(best, time.perf_counter() - start)
    return OVERHEAD_STEPS / best


def _safe(fn, *args) -> float:
    """Baselines only: an absent/broken reference degrades to 0.0 (labeled).
    OUR workloads never go through this — a failure in the code under
    measurement must crash the bench, not publish a silent 0.0."""
    try:
        return fn(*args)
    except Exception:
        return 0.0


def main() -> None:
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    probs, target = _make_data()

    ours_suite, suite_lat, suite_roofline = bench_suite_ours(probs, target)
    ref_suite = _safe(bench_suite_reference, probs, target)

    # per-step workloads run BEFORE the image/detection wall-clocks: FID's
    # gigabyte-scale feature buffers age the tunneled session (dependent
    # dispatch latency measurably grows afterwards), which would deflate the
    # per-step rows with state that has nothing to do with per-step cost
    ours_overhead = bench_overhead_ours()
    # the floor probe runs IMMEDIATELY after the row it bounds — same
    # backend regime, same per-trial call count — so the committed artifact
    # stands behind its own floor_bound_factor with no out-of-band
    # correction (VERDICT round-5 Next #3)
    floor = bench_dispatch_floor()
    # deferred row runs right after the floor probes it is compared against —
    # same backend regime, same shaped comparators
    ours_overhead_deferred, deferred_lat = bench_overhead_deferred_ours()
    # fault instrumentation probe rides the same regime as the deferred row
    # it bounds (same loop shape, same backend state)
    fault_probe = bench_fault_overhead()
    # telemetry probe rides the identical loop right after (same regime):
    # the flight recorder's armed cost must stay under 5% there
    telemetry_probe = bench_telemetry_overhead()
    # device-probe probe rides the identical loop right after the telemetry
    # row it extends (probes disarmed must stay inside its envelope)
    probe_probe = bench_device_probe_overhead()
    sync_probe = bench_sync_per_call()
    # the in-graph functional-core step rides the same regime as the sync
    # rows it obsoletes at scale (ISSUE 16): same suite, same batch, but the
    # merge compiles into the step — zero host collectives to count
    ingraph_probe = bench_ingraph_step()
    # the async-overlap and quant-payload probes ride the same simulated
    # world regime as the sync row they extend (ISSUE 13)
    async_probe = bench_async_sync_overlap()
    quant_probe = bench_sync_quant_payload()
    # durability probes ride the same backend regime as the sync row they
    # extend (same loop shape, same simulated-distributed surface)
    deadline_probe = bench_sync_deadline_overhead()
    journal_probe = bench_journal_write()
    # fleet probe rides the same simulated-world regime as the sync rows
    fleet_probe = bench_fleet_snapshot()
    # streaming probes ride the same regime as the journal/fleet rows they
    # extend (ISSUE 15): the window close reuses the journal pack program,
    # the drift report reuses the fused bincount
    window_probe = bench_window_close()
    drift_probe = bench_drift_report()
    # the tenant-arena probe rides the same regime as the in-graph row it
    # scales out (ISSUE 17): same pure kernels, but N suites share ONE
    # vmapped donated program instead of N dispatch loops
    arena_probe = bench_arena_suites()
    # the ingest-gateway probe rides right after the arena row it fronts
    # (ISSUE 19): same vmapped arena regime, with admission control between
    # the caller and the update machinery
    ingest_probe = bench_ingest_gateway()
    # the cold-start probe rides AFTER the arena row and resets the engine
    # around itself (each boot must start with a cold program registry —
    # that is the thing being measured); rows before it keep their regime
    cold_start_probe = bench_cold_start()
    # the kernel-attack probe runs AFTER the cold-start row (it installs
    # autotuner selections and sweeps variant programs through the engine;
    # it resets the autotuner around itself, and the rows before it keep
    # their untuned regime)
    kernel_probe = bench_kernel_attack()
    boot_floor = bench_bootstrap_shaped_floor()
    ours_overhead_batched = bench_overhead_batched_ours()
    ref_overhead = _safe(bench_overhead_reference)

    real, fake = _fid_data()
    ours_fid = bench_fid_ours(real, fake)
    ref_fid = _safe(bench_fid_baseline, real, fake)

    from tools.bench_map import make_dataset

    map_batches = make_dataset(MAP_IMAGES)
    ours_map = bench_map_ours(map_batches)
    ref_map = _safe(bench_map_baseline, map_batches)

    def ratio(ours, ref, lower_is_better=False):
        if ours <= 0 or ref <= 0:
            return 0.0
        return round(ref / ours if lower_is_better else ours / ref, 3)

    workloads = {
        "fused_suite_update_throughput": {
            "value": round(ours_suite, 1),
            "unit": "samples/s",
            "baseline": round(ref_suite, 1),
            "baseline_hardware": "torch-cpu",
            "vs_baseline": ratio(ours_suite, ref_suite),
            # per-step dispatch-latency percentiles, bucket-interpolated by
            # the telemetry plane's LatencyHistogram (docs/performance.md)
            "latency_ms": suite_lat,
            # roofline columns (ISSUE 12): XLA cost analysis joined with a
            # device-inclusive per-step wall — achieved rates and the bound
            # classification docs/performance.md "Where the time goes" defines
            "achieved_gflops": round(
                suite_roofline.get("achieved_flops_per_s", 0.0) / 1e9, 4
            ),
            "achieved_gbps": round(
                suite_roofline.get("achieved_bytes_per_s", 0.0) / 1e9, 4
            ),
            "arithmetic_intensity": round(
                suite_roofline.get("arithmetic_intensity", 0.0), 4
            ),
            "bound": suite_roofline.get("bound", "unprobed"),
        },
        "fid_wallclock": {
            "value": round(ours_fid, 3),
            "unit": f"s/cycle ({FID_IMAGES * FID_BATCHES * 2} images @299px, update+compute)",
            "baseline": round(ref_fid, 3),
            "baseline_hardware": "torch-cpu-mirror",
            "vs_baseline": ratio(ours_fid, ref_fid, lower_is_better=True),
            # wall-clock timing uses the architecture-identical mirror with
            # deterministic random init on BOTH sides; numeric parity against
            # the real torch-fidelity layout is pinned separately by
            # tests/models/test_checkpoint_layouts.py
            "weights": "random-mirror (architecture-identical; not converted-real)",
        },
        "coco_map_wallclock": {
            "value": round(ours_map, 3),
            "unit": f"s/cycle ({MAP_IMAGES} images, update+compute)",
            "baseline": round(ref_map, 3),
            "baseline_hardware": "torch-cpu",
            "vs_baseline": ratio(ours_map, ref_map, lower_is_better=True),
        },
        "bootstrap_shaped_floor": {
            # genuinely-shaped comparators for the sweep's BootStrapper rows
            # (VERDICT r5 Next #1); ms per chained donated-state program
            "poisson_weighted_row_floor_ms": round(boot_floor["poisson_weighted_row_floor_ms"], 3),
            "multinomial_vmap_floor_ms": round(boot_floor["multinomial_vmap_floor_ms"], 3),
            "num_bootstraps": boot_floor["num_bootstraps"],
            "unit": "ms/program (chained, donated state, trailing sync amortized)",
            "note": boot_floor["note"],
        },
        "per_step_overhead": {
            "value": round(ours_overhead_batched, 1),
            "unit": f"forward steps/s (batched module API: forward_many, {MANY_STEPS}-step chunks)",
            "baseline": round(ref_overhead, 1),
            "baseline_hardware": "torch-cpu",
            "vs_baseline": ratio(ours_overhead_batched, ref_overhead),
            "sync_roundtrip_ms": round(floor["sync_roundtrip_ms"], 1),
            "submission_ms_per_dispatch": round(floor["submission_ms_per_dispatch"], 4),
            "note": (
                "the tunneled backend's blocking sync costs sync_roundtrip_ms "
                "per synchronization (measured on an EMPTY add-one program) — "
                "orders of magnitude above the torch-CPU whole step, which is "
                "why any per-step-synchronizing eager loop is red here; "
                "forward_many amortizes one sync across the chunk"
            ),
        },
        "deferred_per_step": {
            # the SAME reference-style metric(preds, target)-per-step loop as
            # eager_per_step, with deferred micro-batched dispatch on (the
            # default): calls enqueue and flush as stacked scan programs at
            # the METRICS_TPU_DEFER_MAX threshold, so the eager API amortizes
            # itself without a forward_many rewrite. Acceptance bar (ISSUE 3):
            # >= 10x eager_per_step and >= 0.5x the forward_many row.
            "value": round(ours_overhead_deferred, 1),
            "unit": "forward steps/s (eager module API, deferred dispatch on)",
            "baseline": round(ref_overhead, 1),
            "baseline_hardware": "torch-cpu",
            "vs_baseline": ratio(ours_overhead_deferred, ref_overhead),
            "vs_eager_per_step": round(ours_overhead_deferred / ours_overhead, 2)
            if ours_overhead > 0
            else None,
            "vs_forward_many": round(ours_overhead_deferred / ours_overhead_batched, 3)
            if ours_overhead_batched > 0
            else None,
            # per-step percentiles: p50 is the host-side enqueue, the tail is
            # the every-DEFER_MAX-steps flush dispatch — the bimodal shape
            # the mean throughput number averages away
            "latency_ms": deferred_lat,
            "shaped_program_roundtrip_ms": round(floor["shaped_program_roundtrip_ms"], 3),
            "note": (
                "eager API loop, zero code changes: per-step calls enqueue "
                "(host-side append) and the queue flushes as one donated-state "
                "lax.scan program per threshold window — the per-step backend "
                "round trip that bounds eager_per_step amortizes to "
                "1/METRICS_TPU_DEFER_MAX of a dispatch; the residual gap to "
                "forward_many is the per-flush jnp.stack of the queued batches"
            ),
        },
        "fault_overhead": {
            # ISSUE 4 satellite: the failure-domain engine's per-step cost on
            # the hot deferred eager path must be unmeasurable. Same loop as
            # deferred_per_step, timed with the injection checks disarmed
            # (production: one module-attribute read per site) vs armed with
            # a never-firing plan (worst steady-state lookup cost).
            "disarmed_steps_per_s": round(fault_probe["disarmed_steps_per_s"], 1),
            "armed_steps_per_s": round(fault_probe["armed_steps_per_s"], 1),
            "armed_vs_disarmed": round(
                fault_probe["armed_steps_per_s"] / fault_probe["disarmed_steps_per_s"], 3
            )
            if fault_probe["disarmed_steps_per_s"] > 0
            else None,
            "unit": "forward steps/s (eager module API, deferred dispatch on)",
            "note": (
                "armed_vs_disarmed ~1.0 pins that fault classification, "
                "degradation-ladder bookkeeping and the named injection sites "
                "(probe/compile/flush-chunk/donation/sync-gather/host-offload) "
                "cost nothing measurable per step; loop-to-loop jitter on the "
                "backend dominates any difference"
            ),
        },
        "telemetry_overhead": {
            # ISSUE 7: the flight recorder's per-step cost on the hot
            # deferred eager path. Same loop as deferred_per_step, timed with
            # the span recorder disarmed (METRICS_TPU_TELEMETRY=0 — one
            # module-attribute read per site, zero allocation) vs armed (the
            # default: a tuple append into the bounded span ring per event).
            "disarmed_steps_per_s": round(telemetry_probe["disarmed_steps_per_s"], 1),
            "armed_steps_per_s": round(telemetry_probe["armed_steps_per_s"], 1),
            "armed_vs_disarmed": round(
                telemetry_probe["armed_steps_per_s"] / telemetry_probe["disarmed_steps_per_s"], 3
            )
            if telemetry_probe["disarmed_steps_per_s"] > 0
            else None,
            # per-step percentile twins of the ratio pin: the armed pass now
            # ALSO exercises the full-lifetime latency histogram (one bucket
            # increment + cached SLO check per timed span)
            "disarmed_latency_ms": telemetry_probe["disarmed_latency_ms"],
            "armed_latency_ms": telemetry_probe["armed_latency_ms"],
            "unit": "forward steps/s (eager module API, deferred dispatch on)",
            "note": (
                "armed_vs_disarmed >= 0.95 pins the ISSUE-7 acceptance bar "
                "(< 5% armed overhead): per enqueue the recorder appends one "
                "instant-span tuple to a bounded deque, and flush/dispatch/"
                "compile slices amortize over the queue window; armed also "
                "pays the ISSUE-11 latency-histogram path (one bucket-index "
                "increment per TIMED span — instants skip it entirely, so "
                "the hottest enqueue site pays nothing); disarmed, every "
                "site is a single predicate check and allocates nothing "
                "(docs/observability.md)"
            ),
        },
        "sync_per_call": {
            # ISSUE 5: coalesced bucketed sync — one payload collective per
            # suite sync (static fast lane) vs the per-state protocol's
            # 2-per-state-per-metric walk, bit-exact. collectives_per_sync is
            # the cost model: on a tunneled backend each blocking collective
            # costs ~sync_roundtrip_ms (the per_step_overhead row's floor),
            # so the ratio of the two collective counts bounds the sync-time
            # speedup in any real multi-process world.
            "coalesced_syncs_per_s": round(sync_probe["coalesced_syncs_per_s"], 1),
            "coalesced_collectives_per_sync": round(
                sync_probe["coalesced_collectives_per_sync"], 2
            ),
            "per_state_syncs_per_s": round(sync_probe["per_state_syncs_per_s"], 1),
            "per_state_collectives_per_sync": round(
                sync_probe["per_state_collectives_per_sync"], 2
            ),
            # per-cycle latency percentiles for both protocols: the tail of
            # the coalesced cycle is the number the EQuARX-style quantized
            # lane (ROADMAP item 3) must beat, measured the same way the
            # production scrape measures it
            "coalesced_latency_ms": sync_probe["coalesced_latency_ms"],
            "per_state_latency_ms": sync_probe["per_state_latency_ms"],
            # ISSUE 12: the sync decomposition's archived evidence — per-phase
            # wall (pack/serialize/wire/unpack/orchestrate), the effective
            # wire bandwidth the gathered bytes imply, and which phase the
            # cycle is bound by (the 69 ms itemization, per round)
            "coalesced_phases_ms": sync_probe["coalesced_phases_ms"],
            "achieved_gbps": round(sync_probe["achieved_gbps"], 4),
            "bound": sync_probe["bound"],
            "unit": "suite sync+unsync cycles/s (4-metric multi-state suite, simulated world)",
            "note": (
                "coalesced: ONE packed payload collective slot + one donated "
                "engine-cached unpack program per sync; per-state "
                "(METRICS_TPU_SYNC_COALESCE=0): one shape + one payload slot "
                "per state per metric — the collective-slot ratio is the "
                "multi-process round-trip saving (each slot is a blocking "
                "~sync_roundtrip_ms exchange on the tunneled backend)"
            ),
        },
        "ingraph_step": {
            # ISSUE 16: the functional pytree core — the whole suite as ONE
            # jitted donated apply_update program over an epoch-stamped
            # FuncState tree, the in-graph replacement for the host sync
            # plane. host_collectives_per_step == 0 and wire_share == 0 are
            # the cost model: there is no host protocol to pay AT ANY WORLD
            # SIZE (the cross-device merge compiles into the step as
            # lax collectives) — sweep_regress gates both zeros.
            "steps_per_s": round(ingraph_probe["steps_per_s"], 1),
            "ms_per_step": round(ingraph_probe["ms_per_step"], 4),
            "host_collectives_per_step": round(
                ingraph_probe["host_collectives_per_step"], 4
            ),
            "wire_phase_ms": round(ingraph_probe["wire_phase_ms"], 3),
            "wire_share": round(ingraph_probe["wire_share"], 4),
            "latency_ms": ingraph_probe["latency_ms"],
            "devices": ingraph_probe["devices"],
            "unit": "whole-suite in-graph steps/s (4-metric suite, jitted donated FuncState)",
            "note": (
                "state-as-pytree apply_update inside one donated jitted "
                "program; the host sync counters stay flat across the whole "
                "run (zero host round trips — the 69 ms blocking wall and "
                "the ~9 ms async forced wait both go to 0, not merely "
                "hidden) and there is no wire phase in the decomposition at "
                "all; ingraph_spmd_certification pins the same zero at "
                "world 8 with NamedSharding states "
                "(docs/performance.md 'Zero host round trips')"
            ),
        },
        "async_sync_overlap": {
            # ISSUE 13: the wire moved off the critical path. Same suite and
            # per-cycle work, SAME simulated slow transport (each payload
            # collective sleeps simulated_rtt_ms — the tunneled-backend
            # regime where BENCH_r03-r05 pinned a ~69 ms blocking sync);
            # blocking pays the RTT serially, async dispatches and runs the
            # cycle's updates while the wire flies, forcing at the end.
            "blocking_steps_per_s": round(async_probe["blocking_steps_per_s"], 1),
            "async_steps_per_s": round(async_probe["async_steps_per_s"], 1),
            "overlap_speedup": round(async_probe["overlap_speedup"], 3),
            # the proof, from perf_report's overlapped-wire evidence: the
            # share of in-flight wire wall the host never blocked on —
            # sweep_regress gates this round over round (floor 0.5)
            "wire_hidden_fraction": round(async_probe["wire_hidden_fraction"], 4),
            "overlapped_wire_ms": round(async_probe["overlapped_wire_ms"], 3),
            "forced_wait_ms": round(async_probe["forced_wait_ms"], 3),
            "simulated_rtt_ms": async_probe["simulated_rtt_ms"],
            "updates_per_cycle": async_probe["updates_per_cycle"],
            "unit": "update steps/s with one suite sync per cycle (simulated slow transport)",
            "note": (
                "sync_async dispatches the packed payload collective to the "
                "dispatcher thread and overlaps it with the cycle's updates; "
                "compute()/wait() forces with an epoch-fence re-check. "
                "wire_hidden_fraction = (overlapped wire - forced wait) / "
                "overlapped wire, from the sync-dispatch/sync-force span "
                "bracketing (docs/performance.md 'Hiding the wire')"
            ),
        },
        "sync_quant_payload": {
            # ISSUE 13 (EQuARX, arXiv:2506.17615): bytes on the wire per
            # suite sync under the quantized payload lanes, mixed suite
            # (float curve vectors = lossy lane; integer counts = exact
            # carve-out). Off by default; bit-exactness gates stay on unless
            # METRICS_TPU_SYNC_QUANT is explicitly set.
            "f32_bytes_per_sync": quant_probe["f32_bytes_per_sync"],
            "bf16_bytes_per_sync": quant_probe["bf16_bytes_per_sync"],
            "int8_bytes_per_sync": quant_probe["int8_bytes_per_sync"],
            "bf16_reduction": round(quant_probe["bf16_reduction"], 3),
            "int8_reduction": round(quant_probe["int8_reduction"], 3),
            "quant_exact_states": quant_probe["quant_exact_states"],
            "quant_lossy_states": quant_probe["quant_lossy_states"],
            "unit": "bytes gathered per suite sync (binned curves + integer counts)",
            "note": (
                "float states ship bf16 (2 B/elem) or int8 (+4 B f32 scale "
                "rider per state); integer/bool count states and cat sample "
                "rows route around the lossy encoder unchanged, so "
                "all-integer classification suites stay bit-exact under any "
                "tier (quant tier tolerance table: docs/performance.md)"
            ),
        },
        "device_probe_overhead": {
            # ISSUE 12: the sampled device-time probes' cost envelope. Probes
            # DISARMED (the default) must sit inside the telemetry
            # armed≈disarmed band — the dispatch path pays one cached-int
            # compare; armed at EVERY=8, every 8th dispatch blocks until the
            # device finishes (deliberately paid: it buys the device-
            # inclusive wall the roofline ledger joins).
            "disarmed_steps_per_s": round(probe_probe["disarmed_steps_per_s"], 1),
            "armed_steps_per_s": round(probe_probe["armed_steps_per_s"], 1),
            "armed_vs_disarmed": round(
                probe_probe["armed_steps_per_s"] / probe_probe["disarmed_steps_per_s"], 3
            )
            if probe_probe["disarmed_steps_per_s"] > 0
            else None,
            "probe_every": probe_probe["probe_every"],
            "device_probes": probe_probe["device_probes"],
            "unit": "forward steps/s (eager module API, deferred dispatch on, telemetry armed)",
            "note": (
                "disarmed (METRICS_TPU_DEVICE_PROBE_EVERY unset/0): one int "
                "compare per dispatch, nothing allocated — the bench-pinned "
                "default; armed: every Nth dispatch is forced with "
                "block_until_ready and its device-inclusive wall lands in the "
                "device-dispatch:<program> histogram family the roofline "
                "ledger and perf_report() join (docs/performance.md)"
            ),
        },
        "sync_deadline_overhead": {
            # ISSUE 6: the watchdog deadline's healthy-path cost must be
            # unmeasurable — with METRICS_TPU_SYNC_DEADLINE_MS unset the
            # collective is a direct call (zero threads), and even armed with
            # a never-firing deadline the per-sync cost is one daemon-thread
            # handoff. armed≈disarmed is the acceptance pin.
            "disarmed_syncs_per_s": round(deadline_probe["disarmed_syncs_per_s"], 1),
            "armed_syncs_per_s": round(deadline_probe["armed_syncs_per_s"], 1),
            "armed_vs_disarmed": round(
                deadline_probe["armed_syncs_per_s"] / deadline_probe["disarmed_syncs_per_s"], 3
            )
            if deadline_probe["disarmed_syncs_per_s"] > 0
            else None,
            # ISSUE 8: deadline + quorum tier + epoch fencing armed on a
            # healthy transport (the fence is one int compare per collective
            # plus a registry fold per completed sync) — armed≈disarmed is
            # the membership acceptance pin
            "membership_armed_syncs_per_s": round(
                deadline_probe["membership_armed_syncs_per_s"], 1
            ),
            "membership_armed_vs_disarmed": round(
                deadline_probe["membership_armed_syncs_per_s"]
                / deadline_probe["disarmed_syncs_per_s"],
                3,
            )
            if deadline_probe["disarmed_syncs_per_s"] > 0
            else None,
            "unit": "suite sync+unsync cycles/s (2-metric suite, simulated world)",
            "note": (
                "disarmed (default): run_with_deadline is a direct call — "
                "behavior and cost identical to the pre-deadline protocol; "
                "armed: each blocking collective rides a watchdog thread so a "
                "hung peer raises a classified SyncTimeoutFault instead of "
                "blocking forever; membership_armed additionally epoch-fences "
                "every collective and arms the quorum tier (docs/robustness.md)"
            ),
        },
        "fleet_snapshot_overhead": {
            # ISSUE 9: the fleet observability plane's cost in a world of
            # size 1 (the production single-replica default). ZERO
            # collectives per snapshot is the acceptance pin — the local
            # plane serves directly; gathering engages only in a multi-rank
            # (or registry-declared) world, as two collective slots per
            # snapshot (length exchange + padded blob gather).
            "armed_snapshots_per_s": round(fleet_probe["armed_snapshots_per_s"], 1),
            "disarmed_snapshots_per_s": round(fleet_probe["disarmed_snapshots_per_s"], 1),
            "collectives_per_snapshot": round(fleet_probe["collectives_per_snapshot"], 4),
            "latency_ms": fleet_probe["latency_ms"],
            "unit": "fleet_snapshot() calls/s (world size 1, 2-metric suite)",
            "note": (
                "collectives_per_snapshot == 0 pins the world-size-1 "
                "zero-collective contract; armed vs disarmed differ only by "
                "the fleet-snapshot span emit itself — the span-ring "
                "phase-stats reduction (the straggler-attribution input) "
                "runs in BOTH loops, since disarming stops recording but "
                "keeps the retained ring (docs/observability.md Fleet plane)"
            ),
        },
        "journal_write_per_snapshot": {
            # ISSUE 6: one crash-consistent suite snapshot — the engine-cached
            # pack program (shared with the coalesced sync), CRC32 framing,
            # atomic temp+rename, generation-ring rotation.
            "snapshots_per_s": round(journal_probe["snapshots_per_s"], 1),
            "ms_per_snapshot": round(journal_probe["ms_per_snapshot"], 3),
            "record_bytes": journal_probe["record_bytes"],
            "latency_ms": journal_probe["latency_ms"],
            "unit": "save_state() calls/s (4-metric multi-state suite)",
            "note": (
                "bounds the journal(path, every_n) cadence: at every_n=N the "
                "steady-state per-update journaling cost is ms_per_snapshot/N; "
                "with no journal configured the hook is one dict lookup per "
                "update (nothing on the hot path)"
            ),
        },
        "window_close": {
            # ISSUE 15: one fleet-agreed window close on a 4-metric suite —
            # agree the close id, merge the stride state, pack it into a
            # ring slot (the journal pack program, reused). The stride
            # updates stage outside the timer; the row prices the close.
            "closes_per_s": round(window_probe["closes_per_s"], 1),
            "ms_per_close": round(window_probe["ms_per_close"], 3),
            "record_bytes": window_probe["record_bytes"],
            "collectives_per_close": round(window_probe["collectives_per_close"], 4),
            "collectives_per_close_live": round(
                window_probe["collectives_per_close_live"], 4
            ),
            "latency_ms": window_probe["latency_ms"],
            "unit": "close_window() calls/s (4-metric suite, window=8 stride=2)",
            "note": (
                "collectives_per_close == 0 pins the world-size-1 "
                "zero-collective contract; collectives_per_close_live == 1 "
                "pins the one-payload-collective-per-close budget in a "
                "simulated 3-rank world (counted, not timed) — a close that "
                "starts issuing more is a regression tools/sweep_regress.py "
                "fails (docs/performance.md Window-close cost model)"
            ),
        },
        "arena_suites": {
            # ISSUE 17: N concurrent 2-metric suites stacked in ONE
            # MetricArena vs the per-instance Python loop. Per tier:
            # suites/s through the vmapped donated programs, the ratio over
            # the (sampled, linearly extrapolated) loop, and the builds the
            # whole tier cost — bounded by slab buckets + pow2 chunks, not
            # by N. sweep_regress gates the 100k-tier ≥10x floor and the
            # retraces_per_add lifecycle pin.
            "tiers": arena_probe["tiers"],
            "loop_suites_per_s": arena_probe["loop_suites_per_s"],
            "loop_sample": arena_probe["loop_sample"],
            "retraces_per_add": arena_probe["retraces_per_add"],
            "lifecycle_builds": arena_probe["lifecycle_builds"],
            "lifecycle_adds": arena_probe["lifecycle_adds"],
            "lifecycle_buckets": arena_probe["lifecycle_buckets"],
            "slab": arena_probe["slab"],
            "slab_record_bytes": arena_probe["slab_record_bytes"],
            "slabs": arena_probe["slabs"],
            "per_tenant_batch": arena_probe["per_tenant_batch"],
            "unit": "tenant suite-updates/s (2-metric suite per tenant)",
            "note": (
                "one vmapped donated program over the stacked FuncState "
                "trees (arena.py): the per-instance loop pays per-tenant "
                "dispatch, the arena pays one dispatch per pow2 chunk — "
                "compile count stays bounded by the slab-bucket set at any "
                "tenant count (docs/performance.md Tenant arenas)"
            ),
        },
        "ingest_gateway": {
            # ISSUE 19: the admission-controlled front door. Sustained
            # admitted rows/s through offer()+flush() into the arena, the
            # per-offer latency distribution, and the shed fraction at 2x
            # overload with the settlement accounting identity checked
            # exactly — sweep_regress gates shed_fraction_2x at
            # --ingest-shed-ceiling and fails on a broken identity.
            "admitted_updates_per_s": ingest_probe["admitted_updates_per_s"],
            "latency_ms": ingest_probe["latency_ms"],
            "shed_fraction_2x": ingest_probe["shed_fraction_2x"],
            "accounting_exact": ingest_probe["accounting_exact"],
            "tenants": ingest_probe["tenants"],
            "payload_rows": ingest_probe["payload_rows"],
            "payloads_per_flush": ingest_probe["payloads_per_flush"],
            "unit": "admitted tenant-rows/s through the gateway",
            "note": (
                "columnar staging + schema-fingerprint admission in front "
                "of the arena's vmapped update (ingest.py): watermark-"
                "bounded staging, coalesce-before-shed under SLO pressure, "
                "poison quarantine — docs/robustness.md Overload & "
                "admission control"
            ),
        },
        "cold_start": {
            # ISSUE 18: replica-replacement cost with the persistent
            # program cache. warm_boot_compiles is the fleet promise —
            # sweep_regress gates it at --warm-boot-compile-ceiling
            # (default 0.0: a warmed replica compiles NOTHING).
            "cold_first_result_ms": cold_start_probe["cold_first_result_ms"],
            "warm_first_result_ms": cold_start_probe["warm_first_result_ms"],
            "first_result_speedup": cold_start_probe["first_result_speedup"],
            "cold_compiles": cold_start_probe["cold_compiles"],
            "cold_stores": cold_start_probe["cold_stores"],
            "store_bytes": cold_start_probe["store_bytes"],
            "warm_boot_compiles": cold_start_probe["warm_boot_compiles"],
            "warm_hits": cold_start_probe["warm_hits"],
            "replacement_wall_ms": cold_start_probe["replacement_wall_ms"],
            "cold_wall_ms": cold_start_probe["cold_wall_ms"],
            "unit": "ms to first synced compute result (fresh engine boot)",
            "note": (
                "cold boot traces+compiles+stores every program; warmed "
                "boot precompile()s from the on-disk store and serves "
                "first traffic with zero fresh compiles — the two-process "
                "certification (corrupt-entry demotion included) runs in "
                "make dryrun (docs/performance.md Cold start cost model)"
            ),
        },
        "kernel_attack": {
            # ISSUE 20: the roofline-guided variant sweep — per heavy
            # kernel, the reference formulation vs the installed winner
            # (wall, achieved utilization vs the calibrated peaks, winning
            # variant name). sweep_regress gates
            # kernel_min_winner_vs_baseline at --kernel-utilization-floor
            # (default 1.0: an installed winner may never score below the
            # reference floor).
            "kernels": kernel_probe["kernels"],
            "kernel_min_winner_vs_baseline": kernel_probe["kernel_min_winner_vs_baseline"],
            "sweeps": kernel_probe["sweeps"],
            "candidates": kernel_probe["candidates"],
            "disqualified": kernel_probe["disqualified"],
            "sweep_wall_ms": kernel_probe["sweep_wall_ms"],
            "unit": "winner/baseline roofline-score ratio per kernel family",
            "note": (
                "variant sweeps through real Executable dispatch under the "
                "device probes (ops/autotune.py): winners kept per (kernel, "
                "shape class), exactness-checked against the reference "
                "before install, persisted into the progcache store for "
                "zero-sweep warm boots (docs/performance.md Kernel attack)"
            ),
        },
        "drift_report": {
            # ISSUE 15: one PSI/KS drift computation over two 4096-sample
            # raw-state vectors — shared linear binning through the fused
            # bincount, probability-floored histograms, closed-form scores.
            "reports_per_s": round(drift_probe["reports_per_s"], 1),
            "ms_per_report": round(drift_probe["ms_per_report"], 3),
            "sample_size": drift_probe["sample_size"],
            "psi": round(drift_probe["psi"], 4),
            "ks": round(drift_probe["ks"], 4),
            "latency_ms": drift_probe["latency_ms"],
            "unit": "drift_report() calls/s (2x4096 float32 samples, 16 bins)",
            "note": (
                "bounds the drift-scrape cadence: host-side outside the "
                "update hot path entirely — psi/ks columns double as a "
                "determinism canary (fixed seed, fixed shift) "
                "(docs/observability.md Model-monitoring plane)"
            ),
        },
        "eager_per_step": {
            # first-class tracked row (BASELINE.md "eager_per_step"): the
            # reference-style one-metric(preds, target)-per-step loop with
            # deferral pinned OFF (the METRICS_TPU_DEFER=0 behavior).
            "value": round(ours_overhead, 1),
            "unit": "forward steps/s (eager fused single-dispatch forward)",
            "baseline": round(ref_overhead, 1),
            "baseline_hardware": "torch-cpu",
            "vs_baseline": ratio(ours_overhead, ref_overhead),
            # floor-bound evidence: the backend's steady per-program cost for
            # a MINIMAL chained jitted step (scalar add-one) and for a
            # SHAPE-MATCHED one (the fused step's ~6-buffer state pytree +
            # batch value — each output buffer adds tunnel traffic). eager
            # cannot beat 1000/program_roundtrip_ms steps/s while it runs one
            # program per step — when that ceiling is itself below the
            # torch-CPU baseline, a >=1x eager target is structurally
            # unreachable on this backend. floor_bound_factor = eager step
            # time / SHAPE-MATCHED program time; the excess over 1.0 is the
            # python wrapper (~0.4 ms measured) plus session aging.
            "program_roundtrip_ms": round(floor["program_roundtrip_ms"], 3),
            "shaped_program_roundtrip_ms": round(floor["shaped_program_roundtrip_ms"], 3),
            "floor_steps_per_s_ceiling": round(1000.0 / floor["shaped_program_roundtrip_ms"], 1)
            if floor["shaped_program_roundtrip_ms"] > 0
            else None,
            "floor_bound_factor": round(
                (1000.0 / ours_overhead) / floor["shaped_program_roundtrip_ms"], 2
            )
            if ours_overhead > 0 and floor["shaped_program_roundtrip_ms"] > 0
            else None,
            "note": (
                "bounded by the tunneled backend's per-program round trip, "
                "not metric code: a chained program with this metric's exact "
                "buffer profile tops out at floor_steps_per_s_ceiling steps/s "
                "(an EMPTY program at 1000/program_roundtrip_ms) — below the "
                "torch-CPU baseline, so >=1x eager is structurally "
                "unreachable here. Use forward_many/update_many "
                "(per_step_overhead row) to amortize; on a locally-attached "
                "TPU the same eager path has no tunnel in the loop"
            ),
        },
    }
    print(
        json.dumps(
            {
                "metric": "fused_suite_update_throughput",
                "value": round(ours_suite, 1),
                "unit": "samples/s",
                "vs_baseline": ratio(ours_suite, ref_suite),
                "baseline_hardware": "torch-cpu (no CUDA in this environment)",
                "smoke": SMOKE,
                "workloads": workloads,
            }
        )
    )


if __name__ == "__main__":
    main()
