"""Headline benchmark: fused classification metric-suite update throughput.

Workload (BASELINE.md "metric.update()/sec/chip"): per step, one batch of
``(B, C)`` probabilities + integer targets is pushed through a 4-metric suite
(Accuracy, F1 macro, ConfusionMatrix, Precision macro — one stat-scores family
member, one confmat family member). Our path runs the whole suite as ONE jitted
XLA computation with donated state (updates fuse into a single kernel launch);
the baseline is the mounted reference (`/root/reference/src`, TorchMetrics on
torch) running the identical suite on the same host.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
``vs_baseline`` = our elements/sec ÷ reference elements/sec (>1 means faster).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

BATCH, NUM_CLASSES, STEPS, WARMUP, TRIALS = 8192, 128, 50, 5, 3


def _make_data(seed: int = 0):
    rng = np.random.RandomState(seed)
    logits = rng.randn(BATCH, NUM_CLASSES).astype(np.float32)
    probs = np.exp(logits - logits.max(axis=1, keepdims=True))
    probs /= probs.sum(axis=1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, size=(BATCH,))
    return probs, target


def bench_ours(probs: np.ndarray, target: np.ndarray) -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision

    suite = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="macro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "confmat": ConfusionMatrix(num_classes=NUM_CLASSES),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    init, update, compute = suite.as_functions()
    states = init()
    fused_update = jax.jit(update, donate_argnums=(0,))

    p = jnp.asarray(probs)
    t = jnp.asarray(target)
    for _ in range(WARMUP):
        states = fused_update(states, p, t)
    jax.block_until_ready(states)

    # best of TRIALS: host<->device dispatch latency is noisy on tunneled
    # accelerators; the minimum elapsed time reflects the device's capability
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(STEPS):
            states = fused_update(states, p, t)
        jax.block_until_ready(states)
        best = min(best, time.perf_counter() - start)
    # sanity: finalize once so the state is actually consumed
    _ = compute(states)
    return STEPS * BATCH / best


def bench_reference(probs: np.ndarray, target: np.ndarray) -> float:
    sys.path.insert(0, "tests")
    from helpers.reference_oracle import get_reference

    tm = get_reference()
    if tm is None:
        return 0.0
    import torch

    suite = [
        tm.Accuracy(num_classes=NUM_CLASSES, average="macro"),
        tm.F1Score(num_classes=NUM_CLASSES, average="macro"),
        tm.ConfusionMatrix(num_classes=NUM_CLASSES),
        tm.Precision(num_classes=NUM_CLASSES, average="macro"),
    ]
    device = "cuda" if torch.cuda.is_available() else "cpu"
    p = torch.tensor(probs, device=device)
    t = torch.tensor(target, device=device)
    suite = [m.to(device) for m in suite]

    for _ in range(WARMUP):
        for m in suite:
            m.update(p, t)
    if device == "cuda":
        torch.cuda.synchronize()
    best = float("inf")
    for _ in range(TRIALS):
        start = time.perf_counter()
        for _ in range(STEPS):
            for m in suite:
                m.update(p, t)
        if device == "cuda":
            torch.cuda.synchronize()
        best = min(best, time.perf_counter() - start)
    _ = [m.compute() for m in suite]
    return STEPS * BATCH / best


def main() -> None:
    probs, target = _make_data()
    ours = bench_ours(probs, target)
    try:
        ref = bench_reference(probs, target)
    except Exception:
        ref = 0.0
    vs = ours / ref if ref > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "fused_suite_update_throughput",
                "value": round(ours, 1),
                "unit": "samples/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
