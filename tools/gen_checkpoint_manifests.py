"""Generate vendored upstream-checkpoint layout manifests.

Writes ``tests/fixtures/manifests/*.json`` — the exact state-dict key names,
shapes, and dtypes of the real pretrained checkpoints the reference
implementation downloads:

- ``torch_fidelity_inception_v3.json`` — torch-fidelity's
  ``FeatureExtractorInceptionV3`` (the FID/KID/IS weights,
  reference `image/fid.py:41-58`), i.e. the layout of
  ``weights-inception-2015-12-05-6726825d.pth``.
- ``lpips_{alex,vgg,squeeze}.json`` — ``lpips.LPIPS(net=...)`` full module
  state dicts (reference `image/lpip.py:24-77`).
- ``hf_bert_base_uncased.json`` — HF ``BertModel`` (bert-base-uncased config)
  torch state dict (reference `functional/text/bert.py:45-123` loads HF
  checkpoints).

The tables below are transcribed from the *published module definitions*
(torch-fidelity's feature extractor, the lpips package's slice/head layout
over torchvision backbones, transformers' BertModel) — NOT from this repo's
own Flax models or torch mirrors, so the manifests anchor the converters to
upstream reality rather than to in-repo code that could drift with it.
``tests/models/test_checkpoint_layouts.py`` holds everything together:
mirror == manifest, converter(synthetic ckpt from manifest) == Flax-model
manifest, and an end-to-end metric compute from a synthetic real-layout
checkpoint.

This environment has no egress; on a machine with the real artifacts, the
same JSON can be regenerated directly from them to re-verify transcription:
``python tools/gen_checkpoint_manifests.py --from-checkpoint path.pth``.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

_OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tests", "fixtures", "manifests")


# --------------------------------------------------------------- InceptionV3
# torch-fidelity FeatureExtractorInceptionV3: module name -> conv
# (in, out, (kh, kw)). Channel arithmetic: each Mixed_* input is the concat
# of the previous block's branch outputs.

def _conv_bn(name: str, cin: int, cout: int, k) -> List[Tuple[str, List[int], str]]:
    kh, kw = (k, k) if isinstance(k, int) else k
    return [
        (f"{name}.conv.weight", [cout, cin, kh, kw], "float32"),
        (f"{name}.bn.weight", [cout], "float32"),
        (f"{name}.bn.bias", [cout], "float32"),
        (f"{name}.bn.running_mean", [cout], "float32"),
        (f"{name}.bn.running_var", [cout], "float32"),
        (f"{name}.bn.num_batches_tracked", [], "int64"),
    ]


def _mixed_a(name: str, cin: int, pool: int):
    out = []
    out += _conv_bn(f"{name}.branch1x1", cin, 64, 1)
    out += _conv_bn(f"{name}.branch5x5_1", cin, 48, 1)
    out += _conv_bn(f"{name}.branch5x5_2", 48, 64, 5)
    out += _conv_bn(f"{name}.branch3x3dbl_1", cin, 64, 1)
    out += _conv_bn(f"{name}.branch3x3dbl_2", 64, 96, 3)
    out += _conv_bn(f"{name}.branch3x3dbl_3", 96, 96, 3)
    out += _conv_bn(f"{name}.branch_pool", cin, pool, 1)
    return out, 64 + 64 + 96 + pool


def _mixed_b(name: str, cin: int):
    out = []
    out += _conv_bn(f"{name}.branch3x3", cin, 384, 3)
    out += _conv_bn(f"{name}.branch3x3dbl_1", cin, 64, 1)
    out += _conv_bn(f"{name}.branch3x3dbl_2", 64, 96, 3)
    out += _conv_bn(f"{name}.branch3x3dbl_3", 96, 96, 3)
    return out, 384 + 96 + cin


def _mixed_c(name: str, cin: int, c7: int):
    out = []
    out += _conv_bn(f"{name}.branch1x1", cin, 192, 1)
    out += _conv_bn(f"{name}.branch7x7_1", cin, c7, 1)
    out += _conv_bn(f"{name}.branch7x7_2", c7, c7, (1, 7))
    out += _conv_bn(f"{name}.branch7x7_3", c7, 192, (7, 1))
    out += _conv_bn(f"{name}.branch7x7dbl_1", cin, c7, 1)
    out += _conv_bn(f"{name}.branch7x7dbl_2", c7, c7, (7, 1))
    out += _conv_bn(f"{name}.branch7x7dbl_3", c7, c7, (1, 7))
    out += _conv_bn(f"{name}.branch7x7dbl_4", c7, c7, (7, 1))
    out += _conv_bn(f"{name}.branch7x7dbl_5", c7, 192, (1, 7))
    out += _conv_bn(f"{name}.branch_pool", cin, 192, 1)
    return out, 192 * 4


def _mixed_d(name: str, cin: int):
    out = []
    out += _conv_bn(f"{name}.branch3x3_1", cin, 192, 1)
    out += _conv_bn(f"{name}.branch3x3_2", 192, 320, 3)
    out += _conv_bn(f"{name}.branch7x7x3_1", cin, 192, 1)
    out += _conv_bn(f"{name}.branch7x7x3_2", 192, 192, (1, 7))
    out += _conv_bn(f"{name}.branch7x7x3_3", 192, 192, (7, 1))
    out += _conv_bn(f"{name}.branch7x7x3_4", 192, 192, 3)
    return out, 320 + 192 + cin


def _mixed_e(name: str, cin: int):
    out = []
    out += _conv_bn(f"{name}.branch1x1", cin, 320, 1)
    out += _conv_bn(f"{name}.branch3x3_1", cin, 384, 1)
    out += _conv_bn(f"{name}.branch3x3_2a", 384, 384, (1, 3))
    out += _conv_bn(f"{name}.branch3x3_2b", 384, 384, (3, 1))
    out += _conv_bn(f"{name}.branch3x3dbl_1", cin, 448, 1)
    out += _conv_bn(f"{name}.branch3x3dbl_2", 448, 384, 3)
    out += _conv_bn(f"{name}.branch3x3dbl_3a", 384, 384, (1, 3))
    out += _conv_bn(f"{name}.branch3x3dbl_3b", 384, 384, (3, 1))
    out += _conv_bn(f"{name}.branch_pool", cin, 192, 1)
    return out, 320 + 768 + 768 + 192


def inception_manifest() -> Dict[str, Dict]:
    entries: List[Tuple[str, List[int], str]] = []
    entries += _conv_bn("Conv2d_1a_3x3", 3, 32, 3)
    entries += _conv_bn("Conv2d_2a_3x3", 32, 32, 3)
    entries += _conv_bn("Conv2d_2b_3x3", 32, 64, 3)
    entries += _conv_bn("Conv2d_3b_1x1", 64, 80, 1)
    entries += _conv_bn("Conv2d_4a_3x3", 80, 192, 3)
    cin = 192
    for name, pool in (("Mixed_5b", 32), ("Mixed_5c", 64), ("Mixed_5d", 64)):
        block, cin = _mixed_a(name, cin, pool)
        entries += block
    block, cin = _mixed_b("Mixed_6a", cin)
    entries += block
    for name, c7 in (("Mixed_6b", 128), ("Mixed_6c", 160), ("Mixed_6d", 160), ("Mixed_6e", 192)):
        block, cin = _mixed_c(name, cin, c7)
        entries += block
    block, cin = _mixed_d("Mixed_7a", cin)
    entries += block
    for name in ("Mixed_7b", "Mixed_7c"):
        block, cin = _mixed_e(name, cin)
        entries += block
    assert cin == 2048, cin
    entries.append(("fc.weight", [1008, 2048], "float32"))
    entries.append(("fc.bias", [1008], "float32"))
    return {
        key: {
            "shape": shape,
            "dtype": dtype,
            # the 2015-12-05 artifact predates BN's num_batches_tracked
            # buffer; modern re-saves include it. Converters must accept both.
            "optional": key.endswith("num_batches_tracked"),
        }
        for key, shape, dtype in entries
    }


# -------------------------------------------------------------------- LPIPS
# lpips.LPIPS(net=...) full-module state dict: scaling-layer buffers, the
# torchvision backbone sliced as net.slice{k}.{features_index}.*, the learned
# heads registered TWICE (attributes lin{k}.model.1.weight AND the ModuleList
# copy lins.{k}.model.1.weight — same tensors, both present in state_dict()).

_ALEX_CONVS = {0: (3, 64, 11), 3: (64, 192, 5), 6: (192, 384, 3), 8: (384, 256, 3), 10: (256, 256, 3)}
_ALEX_SLICES = {1: [0, 1], 2: [2, 3, 4], 3: [5, 6, 7], 4: [8, 9], 5: [10, 11]}
_ALEX_LINS = [64, 192, 384, 256, 256]

_VGG_CONV_PLAN = [
    (1, [(0, 3, 64), (2, 64, 64)]),
    (2, [(5, 64, 128), (7, 128, 128)]),
    (3, [(10, 128, 256), (12, 256, 256), (14, 256, 256)]),
    (4, [(17, 256, 512), (19, 512, 512), (21, 512, 512)]),
    (5, [(24, 512, 512), (26, 512, 512), (28, 512, 512)]),
]
_VGG_LINS = [64, 128, 256, 512, 512]

# squeezenet1_1 features: conv at 0, Fire modules at 3,4,6,7,9,10,11,12.
# Fire(idx): (squeeze_out, expand_out_each). slice -> fire indices per lpips.
_SQUEEZE_FIRES = {3: (16, 64), 4: (16, 64), 6: (32, 128), 7: (32, 128),
                  9: (48, 192), 10: (48, 192), 11: (64, 256), 12: (64, 256)}
_SQUEEZE_FIRE_IN = {3: 64, 4: 128, 6: 128, 7: 256, 9: 256, 10: 384, 11: 384, 12: 512}
_SQUEEZE_SLICES = {1: [0], 2: [3, 4], 3: [6, 7], 4: [9], 5: [10], 6: [11], 7: [12]}
_SQUEEZE_LINS = [64, 128, 256, 384, 384, 512, 512]


def _lpips_common() -> List[Tuple[str, List[int], str]]:
    return [
        ("scaling_layer.shift", [1, 3, 1, 1], "float32"),
        ("scaling_layer.scale", [1, 3, 1, 1], "float32"),
    ]


def _lpips_heads(channels: List[int]) -> List[Tuple[str, List[int], str]]:
    out = []
    for k, ch in enumerate(channels):
        out.append((f"lin{k}.model.1.weight", [1, ch, 1, 1], "float32"))
    for k, ch in enumerate(channels):
        out.append((f"lins.{k}.model.1.weight", [1, ch, 1, 1], "float32"))
    return out


def lpips_alex_manifest() -> Dict[str, Dict]:
    entries = _lpips_common()
    for slice_k, indices in sorted(_ALEX_SLICES.items()):
        for idx in indices:
            if idx in _ALEX_CONVS:
                cin, cout, k = _ALEX_CONVS[idx]
                entries.append((f"net.slice{slice_k}.{idx}.weight", [cout, cin, k, k], "float32"))
                entries.append((f"net.slice{slice_k}.{idx}.bias", [cout], "float32"))
    entries += _lpips_heads(_ALEX_LINS)
    return {k: {"shape": s, "dtype": d, "optional": False} for k, s, d in entries}


def lpips_vgg_manifest() -> Dict[str, Dict]:
    entries = _lpips_common()
    for slice_k, convs in _VGG_CONV_PLAN:
        for idx, cin, cout in convs:
            entries.append((f"net.slice{slice_k}.{idx}.weight", [cout, cin, 3, 3], "float32"))
            entries.append((f"net.slice{slice_k}.{idx}.bias", [cout], "float32"))
    entries += _lpips_heads(_VGG_LINS)
    return {k: {"shape": s, "dtype": d, "optional": False} for k, s, d in entries}


def lpips_squeeze_manifest() -> Dict[str, Dict]:
    entries = _lpips_common()
    for slice_k, indices in sorted(_SQUEEZE_SLICES.items()):
        for idx in indices:
            if idx == 0:
                entries.append((f"net.slice{slice_k}.0.weight", [64, 3, 3, 3], "float32"))
                entries.append((f"net.slice{slice_k}.0.bias", [64], "float32"))
            else:
                cin = _SQUEEZE_FIRE_IN[idx]
                s_out, e_out = _SQUEEZE_FIRES[idx]
                base = f"net.slice{slice_k}.{idx}"
                entries.append((f"{base}.squeeze.weight", [s_out, cin, 1, 1], "float32"))
                entries.append((f"{base}.squeeze.bias", [s_out], "float32"))
                entries.append((f"{base}.expand1x1.weight", [e_out, s_out, 1, 1], "float32"))
                entries.append((f"{base}.expand1x1.bias", [e_out], "float32"))
                entries.append((f"{base}.expand3x3.weight", [e_out, s_out, 3, 3], "float32"))
                entries.append((f"{base}.expand3x3.bias", [e_out], "float32"))
    entries += _lpips_heads(_SQUEEZE_LINS)
    return {k: {"shape": s, "dtype": d, "optional": False} for k, s, d in entries}


# --------------------------------------------------------------------- BERT

def bert_manifest() -> Dict[str, Dict]:
    """HF ``BertModel`` state dict for the bert-base-uncased config,
    instantiated without weight allocation (meta device) from the installed
    transformers package — the published module definition itself."""
    import torch
    from transformers import BertConfig, BertModel

    cfg = BertConfig()  # defaults ARE bert-base-uncased: 12 layers, 768 hidden
    with torch.device("meta"):
        model = BertModel(cfg)
    out = {}
    for key, value in model.state_dict().items():
        out[key] = {
            "shape": list(value.shape),
            "dtype": str(value.dtype).replace("torch.", ""),
            # position_ids is a non-persistent buffer in modern transformers;
            # old checkpoints include it, new ones omit it
            "optional": "position_ids" in key,
        }
    return out


def main(argv) -> None:
    if "--from-checkpoint" in argv:
        # re-verification path for machines that have the real artifact:
        # print a manifest from the .pth instead of the transcribed tables
        import torch

        path = argv[argv.index("--from-checkpoint") + 1]
        state = torch.load(path, map_location="cpu")
        if isinstance(state, dict) and "state_dict" in state:
            state = state["state_dict"]
        print(json.dumps({k: {"shape": list(v.shape), "dtype": str(v.dtype).replace("torch.", "")} for k, v in state.items()}, indent=1))
        return
    os.makedirs(_OUT_DIR, exist_ok=True)
    manifests = {
        "torch_fidelity_inception_v3.json": inception_manifest(),
        "lpips_alex.json": lpips_alex_manifest(),
        "lpips_vgg.json": lpips_vgg_manifest(),
        "lpips_squeeze.json": lpips_squeeze_manifest(),
        "hf_bert_base_uncased.json": bert_manifest(),
    }
    for name, manifest in manifests.items():
        path = os.path.join(_OUT_DIR, name)
        with open(path, "w") as handle:
            json.dump(manifest, handle, indent=1, sort_keys=True)
        print(f"wrote {len(manifest):4d} keys -> {os.path.relpath(path)}")


if __name__ == "__main__":
    main(sys.argv[1:])
