"""Offline documentation integrity check (the `make docs` stage).

The reference builds sphinx docs in its Makefile (`/root/reference/Makefile:28-31`);
this repo's docs are plain markdown, so the docs stage validates them instead
of rendering: every relative link resolves, every in-repo file path named in
backticks exists, and every `SWEEP_r0N.json` / bench artifact referenced is
present. The registry drift check then pins the docs tables to the canonical
site registries (`faults.FAULT_SITES`, `telemetry.SPAN_SITES` — extracted
statically via `tools.invlint.registry`, no jax import): a new injection site
without a `docs/robustness.md` row, or a new span site without a
`docs/observability.md` row, fails this stage. Exit non-zero with a list of
broken references.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.invlint import registry as _registry  # noqa: E402

# markdown link targets: [text](target)
_LINK = re.compile(r"\[[^\]]*\]\(([^)#]+)(?:#[^)]*)?\)")
# backticked repo paths like `metrics_tpu/ops/binned.py` or `tools/bench_sweep.py`
_PATH = re.compile(
    r"`((?:metrics_tpu|tests|tools|examples|docs)/[A-Za-z0-9_./-]+\.(?:py|md|json|cpp|yml))`"
)
# citations of the REFERENCE repo's layout (torchmetrics), not in-repo paths
_REFERENCE_LAYOUT = ("tests/unittests/", "docs/paper_JOSS/", "docs/source/")
# backticked ROOT-level artifacts (bench records, entry points) — bare names
# like `metric.py` inside layout blocks mean package files, so only names
# matching these artifact patterns are required to exist at the repo root
_ROOT_ARTIFACT = re.compile(
    r"`((?:SWEEP|BENCH|BASELINE|COPYCHECK|MULTICHIP)_?[A-Za-z0-9_.-]*\.(?:json|md)|bench\.py|__graft_entry__\.py|Makefile|pyproject\.toml)`"
)


def _doc_files():
    yield os.path.join(REPO, "README.md")
    yield os.path.join(REPO, "CHANGELOG.md")
    yield os.path.join(REPO, "BASELINE.md")
    docs = os.path.join(REPO, "docs")
    for name in sorted(os.listdir(docs)):
        if name.endswith(".md"):
            yield os.path.join(docs, name)


def _registry_drift() -> list:
    """Every canonical site must have a docs-table row. Indexed families are
    documented with the ``-k`` spelling (``flush-chunk-k``)."""
    broken = []
    tables = (
        ("docs/robustness.md", _registry.fault_sites(), "faults.FAULT_SITES"),
        ("docs/observability.md", _registry.span_sites(), "telemetry.SPAN_SITES"),
    )
    for rel, sites, origin in tables:
        text = open(os.path.join(REPO, rel), encoding="utf-8").read()
        # only markdown TABLE rows count — a prose mention is not the
        # structured per-site row this check promises
        rows = "\n".join(line for line in text.splitlines() if line.lstrip().startswith("|"))
        for site in sites:
            if f"`{site}`" not in rows and f"`{site}-k`" not in rows:
                broken.append(f"{rel}: no table row for registered site `{site}` ({origin})")
    return broken


def main() -> int:
    broken = _registry_drift()
    for path in _doc_files():
        rel = os.path.relpath(path, REPO)
        text = open(path, encoding="utf-8").read()
        base = os.path.dirname(path)
        for match in _LINK.finditer(text):
            target = match.group(1).strip()
            if target.startswith(("http://", "https://", "mailto:")):
                continue
            resolved = os.path.normpath(os.path.join(base, target))
            if not os.path.exists(resolved):
                broken.append(f"{rel}: broken link -> {target}")
        for match in _PATH.finditer(text):
            target = match.group(1)
            if target.startswith(_REFERENCE_LAYOUT):
                continue
            if not os.path.exists(os.path.join(REPO, target)):
                broken.append(f"{rel}: named file missing -> {target}")
        for match in _ROOT_ARTIFACT.finditer(text):
            target = match.group(1)
            if re.search(r"r0?N", target):
                continue  # generic placeholder like `SWEEP_r0N.json`
            if not os.path.exists(os.path.join(REPO, target)):
                broken.append(f"{rel}: root artifact missing -> {target}")
    if broken:
        print("\n".join(broken))
        print(f"\n{len(broken)} broken documentation reference(s)")
        return 1
    print(f"docs ok: {sum(1 for _ in _doc_files())} files, all links and file references resolve")
    return 0


if __name__ == "__main__":
    sys.exit(main())
