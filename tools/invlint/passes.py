"""The five invariant passes.

Each pass is a pure function ``Module -> [Finding]`` over one file's AST.
They encode the distributed-correctness contract PRs 3–8 established in
prose and chaos tests (docs/robustness.md "Enforced invariants" is the
human-readable twin of this file):

1. collective-discipline (INV001/INV002/INV003)
2. retry-purity          (INV101/INV102)
3. fault-taxonomy        (INV201/INV202)
4. telemetry-typing      (INV301/INV302/INV303 — scalar keys AND the
   latency-histogram layout: bounds monotone, family name valid, bucket
   samples counter-classified)
5. warn-once discipline  (INV401)
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from tools.invlint import registry
from tools.invlint.core import (
    Finding,
    Module,
    call_base,
    call_name,
    contains_call,
    has_keyword,
    literal_str_arg,
    mentions_identifier,
    module_mutable_globals,
    walk_calls,
)

#: The transport primitives: every call that reaches one of these issues (or
#: in single-process mode, *accounts*) a host collective. The discipline is
#: enforced where these names are CALLED; their own definitions are the seam
#: and are exempt (the guard belongs to the protocol, not the primitive).
TRANSPORT_PRIMITIVES = frozenset(
    {
        "process_allgather",
        "_host_allgather",
        "_payload_allgather",
        "_intranode_allgather",
        "_internode_allgather",
    }
)

#: The sanctioned IN-GRAPH collectives (``jax.lax``): inside a pure
#: functional-core kernel (``apply_update``/``apply_compute``/``sync_array``)
#: these compile INTO the step program — no host transport runs, so the
#: watchdog-deadline and epoch-audit disciplines (INV001/INV002) do not
#: apply; the compiler schedules them and the epoch fence lives in the state
#: treedef instead (``functional_core.FuncState``). Rank-divergent control
#: flow around one still desyncs the mesh exactly like a host collective —
#: one device tracing a psum the others skip is a compile-time shape error
#: at best and a hang at worst — so INV003 fires unchanged.
INGRAPH_COLLECTIVES = frozenset(
    {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "psum_scatter",
        "all_to_all",
        "ppermute",
    }
)

#: The sanctioned blocking-guard spellings. ``run_with_deadline`` is the
#: per-call watchdog; ``run_inflight`` is its async twin — a transport under
#: it runs on the dispatcher thread of a closure reached via ``submit_async``,
#: and the watchdog deadline is applied at the FORCE (``wait_with_deadline``),
#: the only wall the caller actually blocks on. ``_guarded`` is the
#: mode-dispatching wrapper in ``parallel/bucketing.py`` that picks between
#: them. A transport call lexically inside an argument of any of these (or
#: inside a function whose name is called there) is deadline-guarded.
DEADLINE_GUARD_CALLS = frozenset(
    {"run_with_deadline", "run_inflight", "_guarded", "submit_async"}
)

#: Handler calls that count as routing a caught exception through the fault
#: taxonomy (``ops/faults.py``'s classification surface).
FAULT_ROUTERS = frozenset({"classify", "note_fault", "warn_fault", "demote"})

#: The file that owns the taxonomy — bare ``except Exception`` is its job.
FAULTS_MODULE = "metrics_tpu/ops/faults.py"
PRINTS_MODULE = "metrics_tpu/utils/prints.py"

#: Stats dicts whose string-literal keys are scraped into the snapshot.
STATS_DICT_NAMES = frozenset({"_counters", "_stats"})

#: Prometheus exposition family-name alphabet (after the ``metrics_tpu_``
#: prefix; ``:`` is reserved for recording rules, ``-`` would be mangled).
PROM_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


# --------------------------------------------------------------- pass 1: collectives
def _deadline_delegated_names(mod: Module) -> Set[str]:
    """Function names CALLED inside an argument of a guard call
    (:data:`DEADLINE_GUARD_CALLS`) — their bodies execute under the watchdog
    even though the guard is lexically at the caller (e.g.
    ``run_with_deadline(lambda: _gather_once(...))``, or the async shape
    ``submit_async(lambda: retry_with_backoff(attempt, ...))`` whose deadline
    lands at the force). Only call-position names (and bare callables passed
    directly) count: sweeping up every identifier in the argument would
    exempt any function that happens to share a name with a forwarded
    variable."""
    names: Set[str] = set()
    for call in walk_calls(mod.tree):
        if call_name(call) not in DEADLINE_GUARD_CALLS:
            continue
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            # a bare callable handed straight to the guard
            if isinstance(arg, ast.Name):
                names.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                names.add(arg.attr)
            for sub in walk_calls(arg):
                name = call_name(sub)
                if name is not None:
                    names.add(name)
    return names


def _is_deadline_guarded(mod: Module, call: ast.Call, delegated: Set[str]) -> bool:
    for anc in mod.ancestors(call):
        if isinstance(anc, ast.Call) and call_name(anc) in DEADLINE_GUARD_CALLS:
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) and anc.name in delegated:
            return True
    return False


def _rank_divergent_test(test: ast.AST, caches: Set[str]) -> Optional[str]:
    """Why a branch condition is rank-local (None when it is not)."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call) and call_name(sub) == "process_index":
            return "branches on process_index()"
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident in ("rank", "local_rank") or (ident or "").endswith("_rank"):
            return f"branches on rank-local name {ident!r}"
        if isinstance(sub, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in sub.ops
        ):
            for comp in sub.comparators:
                if isinstance(comp, ast.Name) and comp.id in caches:
                    return f"branches on process-local cache {comp.id!r}"
    return None


def check_collective_discipline(mod: Module) -> List[Finding]:
    """INV001/INV002/INV003 — every transport call must run under the
    watchdog deadline, inside a protocol that audits its collective slots
    against the epoch fence, and never under rank-divergent control flow
    (one rank issuing a collective the others skip is a deadlock)."""
    findings: List[Finding] = []
    delegated = _deadline_delegated_names(mod)
    caches = module_mutable_globals(mod.tree)
    for call in walk_calls(mod.tree):
        name = call_name(call)
        if name in INGRAPH_COLLECTIVES:
            # in-graph SPMD collective: exempt from the host-transport
            # watchdog/audit (INV001/INV002 — there is no host wall to guard
            # and the epoch fence is static state-tree metadata), but held to
            # the rank-symmetry discipline: a rank-divergent branch around an
            # in-graph collective desyncs the compiled mesh program too
            for anc in mod.ancestors(call):
                if isinstance(anc, (ast.If, ast.While)):
                    why = _rank_divergent_test(anc.test, caches)
                    if why is not None:
                        findings.append(
                            mod.finding(
                                call,
                                "INV003",
                                f"in-graph collective {name}() {why} (line {anc.lineno})"
                                " — rank-divergent collectives deadlock the cohort",
                            )
                        )
            continue
        if name not in TRANSPORT_PRIMITIVES:
            continue
        encl = mod.enclosing_functions(call)
        named_encl = [
            f for f in encl if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # the primitive definitions themselves are the seam, not a call site
        if any(f.name in TRANSPORT_PRIMITIVES for f in named_encl):
            continue
        if not _is_deadline_guarded(mod, call, delegated):
            findings.append(
                mod.finding(
                    call,
                    "INV001",
                    f"transport call {name}() is not under a run_with_deadline guard"
                    " — a hung peer blocks this protocol forever",
                )
            )
        if not any(
            call_name(c) == "note_collective" and has_keyword(c, "epoch")
            for f in named_encl
            for c in walk_calls(f)
        ):
            findings.append(
                mod.finding(
                    call,
                    "INV002",
                    f"no note_collective(epoch=...) audit in the protocol around {name}()"
                    " — the stale-collective backstop cannot see this slot",
                )
            )
        for anc in mod.ancestors(call):
            if isinstance(anc, (ast.If, ast.While)):
                why = _rank_divergent_test(anc.test, caches)
                if why is not None:
                    findings.append(
                        mod.finding(
                            call,
                            "INV003",
                            f"transport call {name}() {why} (line {anc.lineno})"
                            " — rank-divergent collectives deadlock the cohort",
                        )
                    )
    return findings


# ------------------------------------------------------------------ pass 2: retries
def _resolve_closure(mod: Module, call: ast.Call) -> Optional[ast.AST]:
    """The closure passed to ``retry_with_backoff`` (arg0 or ``fn=``):
    a Lambda inline, or a FunctionDef resolved by name — nearest enclosing
    scope first, so two protocols may both name their closure ``_attempt``."""
    fn_node: Optional[ast.AST] = call.args[0] if call.args else None
    if fn_node is None:
        for kw in call.keywords:
            if kw.arg == "fn":
                fn_node = kw.value
    if isinstance(fn_node, ast.Lambda):
        return fn_node
    if not isinstance(fn_node, ast.Name):
        return None
    candidates = [
        f
        for f in ast.walk(mod.tree)
        if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)) and f.name == fn_node.id
    ]
    scopes: List[ast.AST] = list(mod.enclosing_functions(call)) + [mod.tree]
    for scope in scopes:
        for f in candidates:
            encl = mod.enclosing_functions(f)
            nearest = encl[0] if encl else mod.tree
            if nearest is scope:
                return f
    return candidates[0] if candidates else None


def _issues_collectives(node: ast.AST) -> bool:
    return contains_call(
        node,
        TRANSPORT_PRIMITIVES
        | {"run_with_deadline", "run_inflight", "_guarded", "_payload_exchange", "note_collective"},
    )


def _mutation_sites(node: ast.AST) -> List[ast.AST]:
    out: List[ast.AST] = []
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Assign, ast.AugAssign)):
            targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            if any(isinstance(t, ast.Attribute) for t in targets):
                out.append(sub)
        elif isinstance(sub, ast.Call) and call_name(sub) in ("setattr", "__setattr__"):
            out.append(sub)
    return out


def check_retry_purity(mod: Module) -> List[Finding]:
    """INV101/INV102 — a closure handed to ``faults.retry_with_backoff`` may
    run MORE THAN ONCE: if it issues collectives it must re-check the epoch
    fence (``sync.check_epoch``) before each issue, and if it mutates object
    state the caller must hold a snapshot/restore so a half-applied attempt
    cannot leak into the retry."""
    findings: List[Finding] = []
    if mod.path == FAULTS_MODULE:
        return findings  # the definition site, not a protocol
    for call in walk_calls(mod.tree):
        if call_name(call) != "retry_with_backoff":
            continue
        closure = _resolve_closure(mod, call)
        if closure is None:
            continue
        if _issues_collectives(closure) and not contains_call(closure, ("check_epoch",)):
            name = getattr(closure, "name", "<lambda>")
            findings.append(
                mod.finding(
                    closure,
                    "INV101",
                    f"retried closure {name!r} issues collectives without calling"
                    " check_epoch inside the closure — a membership change between"
                    " attempts re-issues into the wrong cohort",
                )
            )
        mutations = _mutation_sites(closure)
        if mutations:
            scope_nodes: List[ast.AST] = [closure] + mod.enclosing_functions(call)
            guarded = any(
                mentions_identifier(s, ("snapshot", "restore")) for s in scope_nodes
            )
            if not guarded:
                for m in mutations:
                    findings.append(
                        mod.finding(
                            m,
                            "INV102",
                            "state mutated inside a retried closure with no"
                            " snapshot/restore in scope — a failed attempt leaves"
                            " half-applied state for the retry",
                        )
                    )
    return findings


# ----------------------------------------------------------------- pass 3: taxonomy
def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare `except:`
    if isinstance(t, ast.Name) and t.id in ("Exception", "BaseException"):
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
            for e in t.elts
        )
    return False


def _handler_raises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(sub, ast.Raise) for sub in ast.walk(handler))


def check_fault_taxonomy(mod: Module) -> List[Finding]:
    """INV201/INV202 — broad handlers must not swallow silently: re-raise
    (the caller classifies) or route through the taxonomy
    (classify/note_fault/warn_fault/demote); and every literal site string
    handed to the injection/span machinery must exist in the canonical
    registries, so a typo'd site is a lint error instead of a dead hook."""
    findings: List[Finding] = []
    if mod.path != FAULTS_MODULE:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler) or not _is_broad_handler(node):
                continue
            if _handler_raises(node) or any(
                call_name(c) in FAULT_ROUTERS for c in walk_calls(node)
            ):
                continue
            findings.append(
                mod.finding(
                    node,
                    "INV201",
                    "broad except swallows the exception without re-raising or"
                    " routing through faults.classify/note_fault/warn_fault/demote",
                )
            )
    fault_families = set(registry.fault_sites(mod.root))
    span_names = set(registry.span_sites(mod.root))
    for call in walk_calls(mod.tree):
        name = call_name(call)
        if name in ("inject_faults", "maybe_fail"):
            site = literal_str_arg(call, 0)
            if site is not None and registry.site_family(site) not in fault_families:
                findings.append(
                    mod.finding(
                        call,
                        "INV202",
                        f"injection site {site!r} is not in faults.FAULT_SITES"
                        " — the plan would never fire",
                    )
                )
        elif name == "emit":
            base = call_base(call)
            if base is None or "telemetry" not in base.lower():
                continue
            site = literal_str_arg(call, 0)
            if site is not None and site not in span_names:
                findings.append(
                    mod.finding(
                        call,
                        "INV202",
                        f"span site {site!r} is not in telemetry.SPAN_SITES"
                        " — traces and docs cannot account for it",
                    )
                )
    return findings


# ---------------------------------------------------------- pass 4: telemetry typing
def _stats_keys(mod: Module):
    """Yield ``(node, key)`` for every string-literal stats key:
    ``_counters[...] += / = ...`` subscripts, ``_bump("...")`` calls, and the
    declaring dict literals (``_counters = {...}`` / ``_stats.update({...})``)."""
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in STATS_DICT_NAMES
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    yield node, t.slice.value
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in STATS_DICT_NAMES:
                        for key in node.value.keys:
                            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                                yield key, key.value
        elif isinstance(node, ast.Call):
            if call_name(node) == "_bump":
                key = literal_str_arg(node, 0)
                if key is not None:
                    yield node, key
            elif (
                call_name(node) == "update"
                and call_base(node) in STATS_DICT_NAMES
                and node.args
                and isinstance(node.args[0], ast.Dict)
            ):
                for key in node.args[0].keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        yield key, key.value


def check_telemetry_typing(mod: Module) -> List[Finding]:
    """INV301/INV302 — every key a module counts into the snapshot surface
    must carry a type: counter-prefixed (``telemetry.is_counter_key``) or a
    deliberate gauge carve-out. An untyped key scrapes as a gauge by
    accident AND the fleet merge min/median/maxes it instead of summing —
    the scrape and the aggregate silently disagree about what it means."""
    findings: List[Finding] = []
    seen = set()
    for node, key in _stats_keys(mod):
        anchor = (node.lineno, key)
        if anchor in seen:
            continue
        seen.add(anchor)
        if not PROM_NAME.match(key):
            findings.append(
                mod.finding(
                    node,
                    "INV302",
                    f"stats key {key!r} is not a valid Prometheus family name"
                    " (after sanitization two keys could collide)",
                )
            )
        elif not registry.is_counter_key(key, mod.root) and not registry.is_gauge_carveout(
            key, mod.root
        ):
            findings.append(
                mod.finding(
                    node,
                    "INV301",
                    f"stats key {key!r} is untyped: telemetry.is_counter_key rejects it"
                    " and it is not a gauge carve-out — add a counter prefix or"
                    " carve it out explicitly in ops/telemetry.py",
                )
            )
    return findings


#: The latency-histogram layout literals (single-sourced in
#: ``ops/telemetry.py``; any module declaring them is held to the contract).
HIST_LAYOUT_NAMES = (
    "_HIST_BOUNDS_S", "_HIST_FAMILY", "_HIST_SNAPSHOT_KEY", "_DEVICE_HIST_SITE"
)

#: Alphabet for a histogram SITE prefix (``_DEVICE_HIST_SITE``): it travels
#: as a Prometheus label VALUE and as a snapshot dict key, never as a family
#: name — so ``-`` is fine, but quotes/braces/newlines would corrupt the
#: exposition line and ``:`` is reserved as the per-program separator.
SITE_PREFIX = re.compile(r"^[A-Za-z0-9_.-]+$")


def check_histogram_typing(mod: Module) -> List[Finding]:
    """INV303 — the latency-histogram layout contract. A module declaring
    the layout literals must keep: bucket bounds positive and STRICTLY
    increasing (the cumulative ``le`` exposition stops being monotone
    otherwise, and every scrape-side histogram_quantile silently lies), the
    exposition family stem a valid Prometheus name without the reserved
    ``_bucket``/``_sum``/``_count`` suffixes, and the snapshot key's
    flattened bucket/count/sum samples classifying as COUNTERS under
    ``telemetry.is_counter_key`` (the fleet merge sums what the typing rules
    call a counter — a histogram the merge min/median/maxes is corrupt) with
    the interpolated percentiles staying gauge carve-outs."""
    findings: List[Finding] = []
    decls = {}
    for node in mod.tree.body:
        targets: List[ast.AST] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id in HIST_LAYOUT_NAMES:
                try:
                    decls[t.id] = (node, ast.literal_eval(value))
                except ValueError:
                    findings.append(
                        mod.finding(
                            node,
                            "INV303",
                            f"{t.id} is not a pure literal — the histogram layout"
                            " must stay statically extractable (registry single-sourcing)",
                        )
                    )
    if "_HIST_BOUNDS_S" in decls:
        node, bounds = decls["_HIST_BOUNDS_S"]
        numeric = (
            isinstance(bounds, (tuple, list))
            and bool(bounds)
            and all(isinstance(b, (int, float)) and not isinstance(b, bool) for b in bounds)
        )
        if (
            not numeric
            or any(b <= 0 for b in bounds)
            or any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:]))
        ):
            findings.append(
                mod.finding(
                    node,
                    "INV303",
                    "_HIST_BOUNDS_S bounds must be positive and strictly increasing"
                    " — otherwise the cumulative le exposition stops being monotone",
                )
            )
    if "_HIST_FAMILY" in decls:
        node, fam = decls["_HIST_FAMILY"]
        if (
            not isinstance(fam, str)
            or not PROM_NAME.match(fam)
            or fam.endswith(("_bucket", "_sum", "_count"))
        ):
            findings.append(
                mod.finding(
                    node,
                    "INV303",
                    f"_HIST_FAMILY {fam!r} is not a valid Prometheus histogram family"
                    " stem (the renderer appends the reserved _bucket/_sum/_count"
                    " suffixes and the le label)",
                )
            )
    if "_DEVICE_HIST_SITE" in decls:
        node, site = decls["_DEVICE_HIST_SITE"]
        if not isinstance(site, str) or not SITE_PREFIX.match(site):
            findings.append(
                mod.finding(
                    node,
                    "INV303",
                    f"_DEVICE_HIST_SITE {site!r} is not a label-safe histogram site"
                    " prefix (letters/digits/_/./- only; ':' is reserved for the"
                    " per-program suffix) — a quote or brace would corrupt every"
                    " le-labelled exposition line it reaches",
                )
            )
    if "_HIST_SNAPSHOT_KEY" in decls:
        node, key = decls["_HIST_SNAPSHOT_KEY"]
        counter_samples = (
            f"{key}_site_buckets_1e-06",
            f"{key}_site_count",
            f"{key}_site_sum_s",
        )
        if not isinstance(key, str) or not all(
            registry.is_counter_key(s, mod.root) for s in counter_samples
        ):
            findings.append(
                mod.finding(
                    node,
                    "INV303",
                    f"_HIST_SNAPSHOT_KEY {key!r}: its flattened bucket/count/sum"
                    " samples must classify as counters (telemetry.is_counter_key)"
                    " — the fleet merge would min/median/max exact bucket counts",
                )
            )
        elif not all(
            registry.is_gauge_carveout(f"{key}_site{sfx}", mod.root)
            for sfx in ("_p50_s", "_p95_s", "_p99_s", "_max_s")
        ):
            findings.append(
                mod.finding(
                    node,
                    "INV303",
                    f"_HIST_SNAPSHOT_KEY {key!r}: its interpolated percentile samples"
                    " (_p50_s/_p95_s/_p99_s/_max_s) must stay gauge carve-outs —"
                    " they re-interpolate per read and can fall",
                )
            )
    return findings


# ---------------------------------------------------------------- pass 5: warn-once
def _warnings_aliases(mod: Module) -> tuple:
    """``(module_aliases, bare_warn_names)`` — every spelling this module can
    reach ``warnings.warn`` under: ``import warnings [as w]`` and
    ``from warnings import warn [as w]``."""
    module_aliases: Set[str] = set()
    bare_names: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "warnings":
                    module_aliases.add(alias.asname or "warnings")
        elif isinstance(node, ast.ImportFrom) and node.module == "warnings":
            for alias in node.names:
                if alias.name == "warn":
                    bare_names.add(alias.asname or "warn")
    return module_aliases, bare_names


def check_warn_discipline(mod: Module) -> List[Finding]:
    """INV401 — direct ``warnings.warn`` bypasses both the rank-zero gate and
    the per-owner+domain dedupe; on a hot path that is one warning per step
    per rank. ``faults.warn_fault`` (fault-driven, deduped) and
    ``rank_zero_warn`` (informational) are the sanctioned spellings. Aliased
    spellings (``import warnings as w``, ``from warnings import warn``) are
    resolved through the module's imports so they cannot slip past."""
    if mod.path == PRINTS_MODULE:
        return []  # the one module that may spell it out: it IS the wrapper
    module_aliases, bare_names = _warnings_aliases(mod)
    findings: List[Finding] = []
    for call in walk_calls(mod.tree):
        direct = call_name(call) == "warn" and call_base(call) in module_aliases
        bare = isinstance(call.func, ast.Name) and call.func.id in bare_names
        if direct or bare:
            findings.append(
                mod.finding(
                    call,
                    "INV401",
                    "direct warnings.warn — use faults.warn_fault (deduped, classified)"
                    " or utils.prints.rank_zero_warn (rank-gated)",
                )
            )
    return findings


ALL_PASSES = (
    check_collective_discipline,
    check_retry_purity,
    check_fault_taxonomy,
    check_telemetry_typing,
    check_histogram_typing,
    check_warn_discipline,
)
