"""Invariant linter: AST passes that prove collective discipline, fault
taxonomy, and telemetry typing at review time.

    python -m tools.invlint metrics_tpu tools

The distributed-correctness contract this repo grew across PRs 3–8 (every
collective epoch-fenced + deadline-guarded + audited, retried closures
re-checking the fence, fallbacks classified through ``ops/faults``, snapshot
keys typed by ``telemetry.is_counter_key``) lives here as five static
passes, so a violation is a lint error at review time instead of a chaos
sweep finding after merge. See docs/robustness.md "Enforced invariants" for
each rule with its failing example and the sanctioned pattern.

Findings are ``file:line``-anchored with stable rule ids; suppression is an
inline ``# invlint: allow(RULE) — reason`` pragma or a reasoned entry in
``tools/invlint_baseline.json``. ``make lint`` (wired into ``make ci``)
exits nonzero on any non-baselined finding.
"""
from tools.invlint.core import (  # noqa: F401 — the public API
    BaselineError,
    Finding,
    RULES,
    load_baseline,
    run_paths,
    write_baseline,
)
from tools.invlint import registry  # noqa: F401

DEFAULT_PATHS = ("metrics_tpu", "tools")
DEFAULT_BASELINE = "tools/invlint_baseline.json"
