"""Canonical machine-readable registries, extracted from the source of truth.

`ops/faults.py` owns the injection-site tuple (``FAULT_SITES``) and
`ops/telemetry.py` owns the span-site table (``SPAN_SITES``) plus the
counter/gauge typing rules behind ``is_counter_key``. Three consumers ride
this module so none of them can drift from the package:

- the invariant linter (site-string validation, counter typing) — this
  package;
- ``tools/check_docs.py`` — every registered site must have a docs-table row;
- ``tools/fault_sweep.py`` imports ``faults.FAULT_SITES`` directly (it
  already pays the package import) and asserts sweep coverage against it.

Extraction is AST-based (``ast.literal_eval`` on the module-level literal
assignments), NOT an import of ``metrics_tpu`` — the lint and docs stages
stay stdlib-only and run in milliseconds, with no jax in sight. The
companion test (``tests/tools/test_invlint.py``) pins the parsed values
against the imported package, so the two views cannot diverge silently.
"""
from __future__ import annotations

import ast
import os
from functools import lru_cache
from typing import Dict, Tuple

#: Repo root (this file lives at tools/invlint/registry.py).
ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FAULTS_SRC = os.path.join("metrics_tpu", "ops", "faults.py")
_TELEMETRY_SRC = os.path.join("metrics_tpu", "ops", "telemetry.py")


class RegistryError(RuntimeError):
    """A canonical registry could not be extracted from its source module."""


def _module_literals(rel_path: str, names: Tuple[str, ...], root: str = ROOT) -> Dict[str, object]:
    """Evaluate the module-level literal assignments ``names`` in ``rel_path``."""
    path = os.path.join(root, rel_path)
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError) as err:
        raise RegistryError(f"cannot parse {rel_path}: {err}") from err
    wanted = set(names)
    out: Dict[str, object] = {}
    for node in tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for target in targets:
            if isinstance(target, ast.Name) and target.id in wanted:
                try:
                    out[target.id] = ast.literal_eval(value)
                except ValueError as err:
                    raise RegistryError(
                        f"{rel_path}:{node.lineno}: {target.id} is not a pure literal"
                        f" ({err}); the registry must stay statically extractable"
                    ) from err
    missing = wanted - set(out)
    if missing:
        raise RegistryError(f"{rel_path}: registry name(s) not found: {sorted(missing)}")
    return out


@lru_cache(maxsize=8)
def fault_sites(root: str = ROOT) -> Tuple[str, ...]:
    """The canonical injection-site families (``faults.FAULT_SITES``)."""
    return tuple(_module_literals(_FAULTS_SRC, ("FAULT_SITES",), root)["FAULT_SITES"])


@lru_cache(maxsize=8)
def span_sites(root: str = ROOT) -> Tuple[str, ...]:
    """The canonical span-site names (keys of ``telemetry.SPAN_SITES``)."""
    table = _module_literals(_TELEMETRY_SRC, ("SPAN_SITES",), root)["SPAN_SITES"]
    return tuple(table)


@lru_cache(maxsize=8)
def counter_typing(root: str = ROOT) -> Tuple[Tuple[str, ...], Tuple[str, ...], Tuple[str, ...]]:
    """``(counter_prefixes, gauge_suffixes, gauge_prefixes)`` — the typing
    rules behind ``telemetry.is_counter_key``/``prometheus_text``."""
    lits = _module_literals(
        _TELEMETRY_SRC, ("_COUNTER_PREFIXES", "_GAUGE_SUFFIXES", "_GAUGE_PREFIXES"), root
    )
    return (
        tuple(lits["_COUNTER_PREFIXES"]),
        tuple(lits["_GAUGE_SUFFIXES"]),
        tuple(lits["_GAUGE_PREFIXES"]),
    )


def is_counter_key(key: str, root: str = ROOT) -> bool:
    """``telemetry.is_counter_key``, recomputed from the extracted rules."""
    counter_prefixes, gauge_suffixes, gauge_prefixes = counter_typing(root)
    return (
        key.startswith(counter_prefixes)
        and not key.endswith(gauge_suffixes)
        and not key.startswith(gauge_prefixes)
    )


@lru_cache(maxsize=8)
def histogram_layout(root: str = ROOT) -> Tuple[Tuple[float, ...], str, str]:
    """``(bounds_s, family, snapshot_key)`` — the latency histogram layout
    literals behind ``telemetry.latency_stats`` / ``prometheus_text``'s
    ``le``-labelled families (``_HIST_BOUNDS_S`` / ``_HIST_FAMILY`` /
    ``_HIST_SNAPSHOT_KEY``). Single-sourced like the site tables: the INV303
    pass, this module and the package must agree (companion test pins the
    parse against the import)."""
    lits = _module_literals(
        _TELEMETRY_SRC, ("_HIST_BOUNDS_S", "_HIST_FAMILY", "_HIST_SNAPSHOT_KEY"), root
    )
    return (
        tuple(lits["_HIST_BOUNDS_S"]),
        str(lits["_HIST_FAMILY"]),
        str(lits["_HIST_SNAPSHOT_KEY"]),
    )


@lru_cache(maxsize=8)
def device_dispatch_site(root: str = ROOT) -> str:
    """The per-program device-time family prefix (``telemetry.
    _DEVICE_HIST_SITE``): probed dispatches land in latency-histogram sites
    named ``<prefix>:<program>``, and INV303 holds the literal to the same
    contract as the scalar family stem (label-safe, flattened samples
    classifying as counters)."""
    return str(
        _module_literals(_TELEMETRY_SRC, ("_DEVICE_HIST_SITE",), root)["_DEVICE_HIST_SITE"]
    )


def is_histogram_sample_key(key: str, root: str = ROOT) -> bool:
    """``telemetry.is_histogram_sample_key``, recomputed from the extracted
    layout: a flattened bucket/count/sum sample under the snapshot key."""
    _, _, snapshot_key = histogram_layout(root)
    if not key.startswith(snapshot_key + "_"):
        return False
    return "_buckets_" in key or key.endswith(("_count", "_sum_s"))


def is_gauge_carveout(key: str, root: str = ROOT) -> bool:
    """Whether ``key`` is a DELIBERATE gauge (ratio suffix / health block),
    as opposed to an untyped key that merely fails the counter prefixes."""
    _, gauge_suffixes, gauge_prefixes = counter_typing(root)
    return key.endswith(gauge_suffixes) or key.startswith(gauge_prefixes)


def site_family(site: str) -> str:
    """Collapse an indexed site (``flush-chunk-2``) onto its registry family."""
    head, sep, tail = site.rpartition("-")
    if sep and tail.isdigit():
        return head
    return site
