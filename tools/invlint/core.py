"""Invariant-linter core: findings, pragmas, the baseline, and the runner.

One :class:`Module` is built per scanned file (parse + parent links + pragma
table); every pass is a pure function ``Module -> [Finding]``. Suppression
has exactly two sanctioned shapes:

- an inline pragma ``# invlint: allow(RULE[,RULE...]) — <reason>`` on the
  flagged line or the line directly above it (the reason is REQUIRED — a
  reasonless pragma does not suppress and is itself flagged as ``INV000``);
- a baseline entry in ``tools/invlint_baseline.json`` carrying ``file``,
  ``rule``, ``line`` and a non-empty ``reason``.

For the bare-except rule (``INV201``) an existing reasoned
``# noqa: BLE001 — <reason>`` annotation also counts: that is the idiom the
tree already uses for deliberate broad handlers, and re-stating every one as
a pragma would be churn without information.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from tools.invlint import registry

#: Rule catalogue — ids are stable (baselines and pragmas reference them).
RULES: Dict[str, str] = {
    "INV000": "invlint pragma is malformed or missing its reason",
    "INV001": "transport collective not guarded by run_with_deadline",
    "INV002": "collective protocol missing a note_collective(epoch=...) audit",
    "INV003": "collective issued under control flow keyed on rank-local state",
    "INV101": "retried collective closure does not re-check the epoch fence",
    "INV102": "state mutation inside a retried closure without snapshot/restore in scope",
    "INV201": "bare `except Exception` swallows without routing through faults",
    "INV202": "site string is not in the canonical fault/span registry",
    "INV301": "incremented stats key is untyped (neither counter-prefixed nor a gauge carve-out)",
    "INV302": "stats key is not a valid Prometheus exposition name",
    "INV303": "latency-histogram layout breaks its contract (non-monotone bounds, invalid family stem, or bucket samples not counter-classified)",
    "INV401": "direct warnings.warn (route through faults.warn_fault or rank_zero_warn)",
}

_PRAGMA = re.compile(
    r"#.*?invlint:\s*allow\(([^)]*)\)\s*(?:[—:-]+\s*(\S.*))?"
)
_NOQA_BLE = re.compile(r"#\s*noqa:\s*BLE001\b[^\w]*(\S.*)?")
_RULE_ID = re.compile(r"INV\d{3}")


@dataclass(frozen=True)
class Finding:
    file: str  # repo-relative posix path
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class Module:
    """Everything a pass needs about one scanned file."""

    path: str  # repo-relative posix path
    tree: ast.Module
    lines: List[str]
    root: str = registry.ROOT
    pragmas: Dict[int, Tuple[Set[str], bool]] = field(default_factory=dict)
    pragma_findings: List[Finding] = field(default_factory=list)
    _parents: Dict[int, ast.AST] = field(default_factory=dict)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Lexically enclosing FunctionDef/Lambda chain, innermost first."""
        return [
            a
            for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
        ]

    def finding(self, node_or_line: Any, rule: str, message: str) -> Finding:
        line = node_or_line if isinstance(node_or_line, int) else node_or_line.lineno
        return Finding(self.path, line, rule, message)

    def line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    def has_reasoned_noqa_ble(self, line: int) -> bool:
        m = _NOQA_BLE.search(self.line_text(line))
        return bool(m and m.group(1) and m.group(1).strip())

    def suppressed(self, finding: Finding) -> bool:
        for line in (finding.line, finding.line - 1):
            entry = self.pragmas.get(line)
            if entry is not None:
                rules, has_reason = entry
                if has_reason and finding.rule in rules:
                    return True
        if finding.rule == "INV201" and self.has_reasoned_noqa_ble(finding.line):
            return True
        return False


def _build_parents(mod: Module) -> None:
    for node in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(node):
            mod._parents[id(child)] = node


def _collect_pragmas(mod: Module) -> None:
    for idx, text in enumerate(mod.lines, start=1):
        if "invlint" not in text:
            continue
        m = _PRAGMA.search(text)
        if m is None:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        # only engage when a token is shaped like a rule id — prose that
        # merely *describes* the pragma syntax (docstrings, error messages)
        # is not a suppression attempt
        if not any(_RULE_ID.fullmatch(r) for r in rules):
            continue
        reason = (m.group(2) or "").strip()
        known = {r for r in rules if r in RULES}
        if not known or not reason:
            what = "unknown rule id(s)" if not known else "missing reason"
            mod.pragma_findings.append(
                Finding(
                    mod.path,
                    idx,
                    "INV000",
                    f"pragma does not suppress ({what}); use"
                    " `# invlint: allow(RULE) — <reason>`",
                )
            )
            mod.pragmas[idx] = (known, False)
        else:
            mod.pragmas[idx] = (known, True)


def load_module(path: str, root: str = registry.ROOT) -> Module:
    """Parse one file into a :class:`Module`. Unparseable files raise
    (``SyntaxError``/``OSError``) — the runner reports them as hard errors,
    never a silent skip."""
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    tree = ast.parse(source, filename=path)
    mod = Module(path=rel, tree=tree, lines=source.splitlines(), root=root)
    _build_parents(mod)
    _collect_pragmas(mod)
    return mod


# ------------------------------------------------------------------ AST utils
def call_name(node: ast.Call) -> Optional[str]:
    """The terminal callee name of a call: ``f(...)`` and ``m.f(...)`` -> f."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def call_base(node: ast.Call) -> Optional[str]:
    """For ``m.f(...)``: the name ``m``; None for plain calls."""
    func = node.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id
    return None


def literal_str_arg(node: ast.Call, index: int = 0) -> Optional[str]:
    if len(node.args) > index and isinstance(node.args[index], ast.Constant):
        value = node.args[index].value
        if isinstance(value, str):
            return value
    return None


def has_keyword(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def contains_call(node: ast.AST, names: Iterable[str]) -> bool:
    names = set(names)
    return any(call_name(c) in names for c in walk_calls(node))


def mentions_identifier(node: ast.AST, substrings: Sequence[str]) -> bool:
    """Whether any Name/Attribute identifier in ``node`` contains one of
    ``substrings`` (case-insensitive) — the loose "in scope" predicate."""
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ident = sub.name
        if ident is not None:
            low = ident.lower()
            if any(s in low for s in substrings):
                return True
    return False


def module_mutable_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable containers (dict/set literals,
    comprehensions, ``dict()``/``set()`` calls) — process-local caches by
    construction, which is what makes branching on them rank-divergent."""
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.Set, ast.DictComp, ast.SetComp)) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("dict", "set", "defaultdict", "OrderedDict")
        )
        if not mutable:
            continue
        for target in targets:
            if isinstance(target, ast.Name):
                out.add(target.id)
    return out


# ------------------------------------------------------------------- baseline
class BaselineError(ValueError):
    """The baseline file is malformed (every entry needs file/rule/line and a
    non-empty reason — a baseline without reasons is just a mute button)."""


def load_baseline(path: str) -> List[Dict[str, Any]]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        try:
            data = json.load(fh)
        except ValueError as err:
            raise BaselineError(f"{path}: not valid JSON ({err})") from err
    entries = data.get("findings") if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: expected a list (or {{'findings': [...]}})")
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise BaselineError(f"{path}: entry {i} is not an object")
        for key in ("file", "rule", "line", "reason"):
            if key not in entry:
                raise BaselineError(f"{path}: entry {i} is missing {key!r}")
        if entry["rule"] not in RULES:
            raise BaselineError(f"{path}: entry {i} names unknown rule {entry['rule']!r}")
        if not isinstance(entry["line"], int):
            raise BaselineError(f"{path}: entry {i} line must be an integer")
        if not str(entry["reason"]).strip():
            raise BaselineError(
                f"{path}: entry {i} ({entry['file']}:{entry['line']} {entry['rule']})"
                " has an empty reason — baselined findings require a written reason"
            )
    return entries


def write_baseline(path: str, findings: Sequence[Finding], reason: str) -> None:
    """Serialize ``findings`` as a baseline (one shared placeholder reason —
    meant as a starting point for a human to edit, not a final artifact)."""
    entries = [
        {"file": f.file, "line": f.line, "rule": f.rule, "message": f.message, "reason": reason}
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule))
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"findings": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


def _baseline_key(entry: Dict[str, Any]) -> Tuple[str, str, int]:
    return (str(entry["file"]), str(entry["rule"]), int(entry["line"]))


# --------------------------------------------------------------------- runner
def iter_python_files(
    paths: Sequence[str], root: str = registry.ROOT, errors: Optional[List[str]] = None
) -> Iterator[str]:
    for raw in paths:
        path = raw if os.path.isabs(raw) else os.path.join(root, raw)
        if not os.path.exists(path):
            # a typo'd path must be a hard error, not a silently-empty scan
            # that would turn the CI gate into a no-op
            if errors is not None:
                errors.append(f"{raw}: path does not exist")
            continue
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def run_paths(
    paths: Sequence[str],
    *,
    root: str = registry.ROOT,
    baseline: Optional[Sequence[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Lint every ``.py`` file under ``paths``. Returns::

        {"findings": [...],        # reported (non-suppressed, non-baselined)
         "baselined": [...], "pragma_suppressed": int,
         "stale_baseline": [...],  # entries matching nothing anymore
         "files": int, "errors": [...]}
    """
    from tools.invlint import passes

    all_findings: List[Finding] = []
    pragma_suppressed = 0
    errors: List[str] = []
    scanned: Set[str] = set()
    files = 0
    for path in iter_python_files(paths, root, errors):
        files += 1
        try:
            mod = load_module(path, root)
        except (SyntaxError, UnicodeDecodeError, OSError) as err:
            errors.append(f"{path}: {err}")
            continue
        scanned.add(mod.path)
        raw = list(mod.pragma_findings)
        for check in passes.ALL_PASSES:
            raw.extend(check(mod))
        for finding in raw:
            if mod.suppressed(finding):
                pragma_suppressed += 1
            else:
                all_findings.append(finding)

    baselined: List[Finding] = []
    reported: List[Finding] = []
    entries = list(baseline or [])
    keys = {_baseline_key(e) for e in entries}
    matched: Set[Tuple[str, str, int]] = set()
    for finding in all_findings:
        key = (finding.file, finding.rule, finding.line)
        if key in keys:
            matched.add(key)
            baselined.append(finding)
        else:
            reported.append(finding)
    if files == 0 and not errors:
        errors.append(f"no Python files found under {list(paths)!r} — nothing was linted")
    # staleness is only decidable for files this run actually scanned — a
    # subset run must not advise pruning entries that still fire elsewhere
    stale = [
        e
        for e in entries
        if str(e["file"]) in scanned and _baseline_key(e) not in matched
    ]
    reported.sort(key=lambda f: (f.file, f.line, f.rule))
    return {
        "findings": reported,
        "baselined": baselined,
        "pragma_suppressed": pragma_suppressed,
        "stale_baseline": stale,
        "files": files,
        "errors": errors,
    }
