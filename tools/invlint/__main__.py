"""CLI for the invariant linter (the ``make lint`` entry point).

Exit codes: 0 clean, 1 non-baselined findings, 2 usage/baseline errors.
"""
from __future__ import annotations

import argparse
import os
import sys

# `python tools/invlint/__main__.py` (no -m): make the repo root importable
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.invlint import DEFAULT_BASELINE, DEFAULT_PATHS, RULES  # noqa: E402
from tools.invlint.core import BaselineError, load_baseline, run_paths, write_baseline  # noqa: E402
from tools.invlint.registry import ROOT  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.invlint",
        description="AST invariant linter: collective discipline, retry purity,"
        " fault taxonomy, telemetry typing, warn-once discipline.",
    )
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help=f"files/directories to lint (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument(
        "--baseline", default=os.path.join(ROOT, DEFAULT_BASELINE),
        help="baseline JSON of accepted findings (every entry needs a reason)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline file"
    )
    parser.add_argument(
        "--write-baseline", metavar="PATH",
        help="write current findings to PATH as a baseline skeleton"
        " (placeholder reasons — edit before committing) and exit 0",
    )
    parser.add_argument("--list-rules", action="store_true", help="print the rule catalogue")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    try:
        baseline = [] if args.no_baseline else load_baseline(args.baseline)
    except BaselineError as err:
        print(f"invlint: {err}", file=sys.stderr)
        return 2

    report = run_paths(args.paths, baseline=baseline)
    if report["errors"]:
        for err in report["errors"]:
            print(f"invlint: ERROR {err}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(
            args.write_baseline,
            report["findings"],
            reason="TODO: replace with the real reason this finding is accepted",
        )
        print(
            f"invlint: wrote {len(report['findings'])} finding(s) to"
            f" {args.write_baseline} — fill in real reasons before committing"
        )
        return 0

    for finding in report["findings"]:
        print(finding.render())
    for entry in report["stale_baseline"]:
        print(
            f"invlint: stale baseline entry {entry['file']}:{entry['line']}"
            f" {entry['rule']} (no longer fires — prune it)",
            file=sys.stderr,
        )
    print(
        f"invlint: {len(report['findings'])} finding(s)"
        f" ({len(report['baselined'])} baselined,"
        f" {report['pragma_suppressed']} pragma-suppressed)"
        f" across {report['files']} file(s)"
    )
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
