"""Chaos scenario sweep: scripted MULTI-fault failure sequences.

``tools/fault_sweep.py`` certifies one injected fault per site; production
incidents arrive in sequences — a peer hangs mid-collective, and the compile
that the recovery re-probe triggers dies under the same pressure; a process
crashes AND its newest journal generation is torn. Each scenario here drives
one such sequence end to end and asserts the elastic-durability invariant:

    **bit-exact result or classified raise — never silent corruption.**

Every observed value is either identical to the step-by-step oracle, or the
call raised a classified :class:`FaultError`; local state stays intact and
retryable across every failure, and the ladders re-promote once the faults
clear.

Scenarios:

- ``timeout-then-compile-on-reprobe`` — a deadline-armed suite sync times
  out (hung transport, ``METRICS_TPU_SYNC_DEADLINE_MS``); with
  ``METRICS_TPU_SYNC_DEGRADED=local`` compute serves the bit-exact local
  value; then the healed transport's recovery re-probe hits an injected
  COMPILE fault while rebuilding the pack program — the sync-pack ladder
  absorbs it (per-state fallback), still bit-exact, no raise.
- ``crash-with-torn-journal`` — an auto-journaled suite "crashes"; the
  newest generation is additionally corrupted (flipped byte). Restore must
  demote to the previous good generation (classified ``journal`` fault),
  and replaying the lost tail must land bit-exactly on the uninterrupted
  oracle.
- ``pack-then-gather-fault`` — a sync-pack fault demotes to the per-state
  protocol whose gather then ALSO fails past its retry budget: the sync
  must raise classified with local state bit-exact and retryable, and the
  post-fault retry must succeed.
- ``flush-fault-during-journal-save`` — a deferred-queue flush chunk dies
  inside ``save_state``'s observation barrier: the eager replay absorbs it
  and the written record must still load bit-exactly.
- ``kill-rank-quorum-rejoin`` — a 3-rank world loses rank 2 mid-sync: K
  watchdog timeouts auto-declare it dead (peer prober), the epoch bumps, and
  ``METRICS_TPU_SYNC_DEGRADED=quorum`` serves the BIT-EXACT merge over the
  surviving subgroup {0,1}; the restarted rank rejoins (journal restore +
  epoch bump) and the post-rejoin full-world sync is bit-exact vs an
  uninterrupted run — with ZERO stale-epoch collectives issued
  (counter-asserted).
- ``stale-epoch-collective`` — a membership change races a sync's retry: the
  epoch fence raises the classified ``EpochFault`` (the stale retry never
  reaches the transport), local state bit-exact and retryable at the new
  epoch.
- ``barrier-with-torn-generation`` — a ``checkpoint_barrier`` fleet journals
  at one agreed epoch-stamped step; the newest generation tears; ``rejoin``
  demotes to the previous good generation and a survivor's handoff record
  (one bucketed state record) fast-forwards the rejoiner to the barrier
  state bit-exactly.
- ``rank-dies-mid-window-close`` — a 3-rank world loses a rank mid
  ``Windowed.close_window()``: the epoch fence classifies the interrupted
  close as ``EpochFault`` (never a torn window — ring and live accumulator
  bit-intact), the survivors re-close at the new epoch, and the window
  value is bit-exact vs the uninterrupted fleet-level oracle.
- ``torn-window-ring-slot`` — a crashed ``Windowed`` restores its on-disk
  ring with the newest generation of one slot torn: the slot demotes to its
  previous good generation (classified, counted), so the recovered window
  is the previous good window — re-accumulated only from records that
  verify, never from corrupt bytes.
- ``burst-arrival-shed`` — a 2x-overload arrival burst at a bounded
  ``IngestGateway``: watermarks shed exactly the excess (never exceeded,
  byte- and row-asserted per offer), the settlement accounting identity is
  exact, and the admitted rows land bit-exactly on the oracle that saw only
  the admitted payloads.
- ``poison-payload-quarantine`` — a poison storm at the gateway door
  (schema mismatch, NaN/Inf storm, an injected ``ingest-admit`` fault):
  every poison classifies into the bounded quarantine ring without a raise,
  and the target's state stays bit-intact.
- ``slow-consumer-backlog`` — a stalled consumer lets the backlog climb
  while the SLO budget fires: the gateway demotes to the degraded tier,
  coalesces same-schema load instead of growing the tail, sheds the rest;
  the woken consumer's drain absorbs an injected ``ingest-shed`` apply
  fault (quarantined, drain continues) and the clean follow-up flush walks
  the recovery edge back to the normal tier — accounting exact throughout.

``--fast`` runs everything except the deferral interaction (the
``make faults`` / CI subset); the full sweep adds it. One JSON line per
scenario; non-zero exit on any violation.
"""
from __future__ import annotations

import copy
import json
import os
import sys
import tempfile
import time
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("METRICS_TPU_VALIDATION", "first")
os.environ.setdefault("METRICS_TPU_SYNC_BACKOFF_MS", "0")

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_DIR not in sys.path:
    sys.path.insert(0, _REPO_DIR)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import metrics_tpu as mt  # noqa: E402
import metrics_tpu.metric as metric_mod  # noqa: E402
from metrics_tpu.ops import engine, faults  # noqa: E402
from metrics_tpu.ops import journal as journal_mod  # noqa: E402
from metrics_tpu.parallel import bucketing  # noqa: E402
from metrics_tpu.parallel import sync as psync  # noqa: E402
from metrics_tpu.utils.exceptions import EpochFault, FaultError  # noqa: E402

RNG = np.random.RandomState(0)
P = jnp.asarray(RNG.rand(48).astype(np.float32))
T = jnp.asarray(RNG.randint(0, 2, 48))
DIST_ON = lambda: True  # noqa: E731


def _eq(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and np.array_equal(a, b)


def _suite():
    return mt.MetricCollection({"mean": mt.MeanMetric(), "acc": mt.Accuracy()})


class _env:
    """Scoped env overrides + transport/dist patches, restored on exit."""

    def __init__(self, **env):
        self.env = env

    def __enter__(self):
        self.saved_env = {k: os.environ.get(k) for k in self.env}
        for k, v in self.env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        self.saved_payload = bucketing._payload_allgather
        self.saved_host = bucketing._host_allgather
        self.saved_dist = metric_mod._dist_available
        return self

    def hang_transport(self, seconds: float = 0.5):
        # the abandoned call must not re-enter XLA after the watchdog fires
        # (a daemon thread inside a jax dispatch at interpreter exit can
        # abort process teardown); its result is discarded anyway
        def hung(x):
            time.sleep(seconds)
            raise RuntimeError("abandoned hung collective (watchdog timed out long ago)")

        bucketing._payload_allgather = hung

    def heal_transport(self):
        bucketing._payload_allgather = self.saved_payload

    def simulate_distributed(self):
        metric_mod._dist_available = lambda: True

    def __exit__(self, *exc):
        bucketing._payload_allgather = self.saved_payload
        bucketing._host_allgather = self.saved_host
        metric_mod._dist_available = self.saved_dist
        psync.reset_membership()
        for k, v in self.saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def scenario_timeout_then_compile() -> dict:
    """Deadline timeout mid-suite -> degraded local compute -> healed
    transport's recovery re-probe hits a compile fault -> sync-pack ladder
    absorbs it per-state, bit-exact throughout, zero raises."""
    engine.reset_engine()
    faults.set_recovery_policy(steps=1)
    suite = _suite()
    suite.update(P, T)
    oracle = {k: np.asarray(v) for k, v in copy.deepcopy(suite).compute().items()}
    with _env(METRICS_TPU_SYNC_DEADLINE_MS="80", METRICS_TPU_SYNC_DEGRADED="local") as env:
        env.simulate_distributed()
        env.hang_transport(0.5)
        degraded_vals = {k: np.asarray(v) for k, v in suite.compute().items()}
        ok = all(_eq(degraded_vals[k], oracle[k]) for k in oracle)
        ok = ok and suite.sync_health()["degraded"]
        ok = ok and engine.engine_stats()["sync_deadline_timeouts"] >= 1
        # transport heals; the recovery edge (steps=1) re-probes the full
        # sync on the next compute — and that re-probe's program build dies
        env.heal_transport()
        engine.reset_engine()  # force the re-probe to actually compile
        for _, m in suite.items(keep_base=True, copy_state=False):
            m._computed = None
        with faults.inject_faults("compile", count=1) as plan:
            reprobe_vals = {k: np.asarray(v) for k, v in suite.compute().items()}
        ok = ok and plan.fired >= 1
        # the compile fault demoted the coalescer, not the result: the
        # per-state fallback completed the sync (1-process gather = identity)
        ok = ok and all(_eq(reprobe_vals[k], oracle[k]) for k in oracle)
        ok = ok and not suite.sync_health()["degraded"]
    return {"scenario": "timeout-then-compile-on-reprobe", "ok": bool(ok)}


def scenario_crash_with_torn_journal() -> dict:
    """Auto-journaled suite crashes AND its newest generation is torn:
    restore demotes to the previous good generation (classified journal
    fault) and the replayed tail lands bit-exactly on the oracle."""
    engine.reset_engine()
    d = tempfile.mkdtemp(prefix="mt-chaos-")
    path = os.path.join(d, "suite.journal")
    batches = [
        (jnp.asarray(RNG.rand(16).astype(np.float32)), jnp.asarray(RNG.randint(0, 2, 16)))
        for _ in range(3)
    ]
    live = _suite()
    live.journal(path, every_n=1)
    for p, t in batches:
        live.update(p, t)
    oracle = {k: np.asarray(v) for k, v in live.compute().items()}
    # crash: the process state is gone; the newest generation is ALSO torn
    with open(path, "r+b") as fh:
        fh.seek(30)
        byte = fh.read(1)
        fh.seek(30)
        fh.write(bytes([byte[0] ^ 0xFF]))
    j0 = engine.engine_stats()["fault_journal"]
    restored = _suite()
    gen = restored.load_state(path)
    ok = gen == 1  # demoted to the previous good generation
    ok = ok and engine.engine_stats()["fault_journal"] > j0
    restored.update(*batches[2])  # replay the tail lost with generation 0
    got = {k: np.asarray(v) for k, v in restored.compute().items()}
    ok = ok and all(_eq(got[k], oracle[k]) for k in oracle)
    return {"scenario": "crash-with-torn-journal", "ok": bool(ok), "demoted_to_generation": gen}


def scenario_pack_then_gather() -> dict:
    """sync-pack fault demotes to per-state, whose gather then also fails
    past its budget: classified raise, state bit-exact and retryable."""
    engine.reset_engine()
    m = mt.MeanMetric()
    m.update(jnp.asarray([2.0, 4.0]))
    before = {k: np.asarray(v) for k, v in m.metric_state.items()}
    raised = False
    with faults.inject_faults("sync-pack", count=1):
        with faults.inject_faults("sync-gather", count=100):
            try:
                m.sync(distributed_available=DIST_ON)
            except FaultError:
                raised = True  # classified, never a bare Exception
    after = {k: np.asarray(v) for k, v in m.metric_state.items()}
    ok = raised and all(_eq(after[k], before[k]) for k in before)
    ok = ok and not m._is_synced
    m.sync(distributed_available=DIST_ON)  # faults cleared: retry succeeds
    m.unsync()
    ok = ok and _eq(m.compute(), np.asarray(3.0))
    return {"scenario": "pack-then-gather-fault", "ok": bool(ok)}


def scenario_flush_fault_during_journal_save() -> dict:
    """A deferred flush chunk dies inside save_state's observation barrier:
    the eager replay absorbs it and the record still loads bit-exactly."""
    engine.reset_engine()
    engine.set_deferred_dispatch(True)
    d = tempfile.mkdtemp(prefix="mt-chaos-")
    path = os.path.join(d, "m.journal")
    m = mt.MeanMetric()
    for _ in range(6):
        m.update(P)
    with faults.inject_faults("flush-chunk-0", count=1) as plan:
        m.save_state(path)
    engine.set_deferred_dispatch(False)
    oracle = mt.MeanMetric()
    for _ in range(6):
        oracle.update(P)
    engine.set_deferred_dispatch(True)
    fresh = mt.MeanMetric()
    gen = fresh.load_state(path)
    ok = plan.fired >= 1 and gen == 0
    ok = ok and _eq(fresh.compute(), np.asarray(oracle.compute()))
    ok = ok and _eq(m.compute(), np.asarray(oracle.compute()))
    return {"scenario": "flush-fault-during-journal-save", "ok": bool(ok)}


def scenario_kill_rank_quorum_rejoin() -> dict:
    """3-rank world loses rank 2 mid-sync: K timeouts auto-declare it dead
    (epoch bump), METRICS_TPU_SYNC_DEGRADED=quorum serves the bit-exact
    merge over survivors {0,1}; rank 2 restores its journal, rejoins (next
    epoch), and the post-rejoin full-world sync is bit-exact vs an
    uninterrupted run. Zero stale-epoch collectives issued, counter-asserted."""
    from metrics_tpu.ops import progcache

    engine.reset_engine()
    psync.reset_membership()
    faults.set_recovery_policy(steps=1)
    d = tempfile.mkdtemp(prefix="mt-chaos-")
    rank2_path = os.path.join(d, "rank2.journal")
    try:
        with _env(
            METRICS_TPU_SYNC_DEADLINE_MS="80",
            METRICS_TPU_SYNC_DEGRADED="quorum",
            METRICS_TPU_SYNC_RETRIES="1",
            METRICS_TPU_SYNC_DEAD_AFTER="2",
            # the revived rank must serve its first post-rejoin compute
            # without a recompile stall: every program the pre-kill world
            # compiled is exported to this store, and the post-kill
            # reset_engine() below simulates the replacement process
            METRICS_TPU_PROGCACHE="1",
            METRICS_TPU_PROGCACHE_DIR=os.path.join(d, "progstore"),
        ) as env:
            progcache.configure(reset=True)
            env.simulate_distributed()
            suites = []
            for r in range(3):
                s = _suite()
                s.update(jnp.asarray(np.float32([1.0 + 2 * r, 3.0 + 2 * r])), jnp.asarray([0, 1]))
                suites.append(s)
            suites[2].save_state(rank2_path)  # rank 2 journaled before it dies

            # oracles: a suite fed the survivors' (and all ranks') batches —
            # sum-reduced states make sequential updates == the rank merge
            def oracle_over(rs):
                o = _suite()
                for r in rs:
                    o.update(jnp.asarray(np.float32([1.0 + 2 * r, 3.0 + 2 * r])), jnp.asarray([0, 1]))
                return {k: np.asarray(v) for k, v in o.compute().items()}

            quorum_oracle = oracle_over([0, 1])
            full_oracle = oracle_over([0, 1, 2])
            local_oracle = oracle_over([0])

            def trees(live=(0, 1, 2)):
                return [
                    [
                        n
                        for _, m in suites[r].items(keep_base=True, copy_state=False)
                        for n in bucketing.tree_nodes(m)
                    ]
                    for r in live
                ]

            killed = {"dead": False}
            psync.set_expected_world(3)
            psync.set_peer_prober(lambda: [2])

            def rows():
                if not killed["dead"]:
                    return trees()[1:]
                alive = psync.surviving_members()
                if alive is None:
                    return None  # dead peer undeclared: the full world hangs
                return [t for r, t in zip((0, 1, 2), trees()) if r in alive and r != 0]

            def pack(nodes):
                for n in nodes:
                    n._canonicalize_list_states()
                entries, values = bucketing._collect(nodes)
                return bucketing._pack(entries, values)

            def host(vec):
                rr = rows()
                if rr is None:
                    time.sleep(0.5)
                    raise RuntimeError("abandoned hung metadata exchange (dead peer)")
                return np.stack([np.asarray(vec)] + [np.asarray(pack(t)[1]) for t in rr])

            def payload(x):
                rr = rows()
                if rr is None:
                    time.sleep(0.5)
                    raise RuntimeError("abandoned hung collective (dead peer)")
                packs = [pack(t)[0] for t in rr]
                pad = int(x.shape[0])
                return jnp.stack([x] + [jnp.pad(p, (0, pad - int(p.shape[0]))) for p in packs])

            bucketing._host_allgather = host
            bucketing._payload_allgather = payload

            # steady state before the kill: one full-world sync so every
            # program the fleet dispatches (pack AND unpack) compiles — and,
            # with the persistent program cache on, lands in the store the
            # replacement process will boot from
            pre_kill = {k: np.asarray(v) for k, v in suites[0].compute().items()}
            ok = all(_eq(pre_kill[k], full_oracle[k]) for k in full_oracle)
            for _, m in suites[0].items(keep_base=True, copy_state=False):
                m._computed = None
            killed["dead"] = True

            # kill-rank mid-sync -> K timeouts -> dead declared -> quorum serve
            got = {k: np.asarray(v) for k, v in suites[0].compute().items()}
            ok = ok and all(_eq(got[k], quorum_oracle[k]) for k in quorum_oracle)
            ok = ok and not all(_eq(got[k], full_oracle[k]) for k in full_oracle)
            ok = ok and not all(_eq(got[k], local_oracle[k]) for k in local_oracle)
            stats = engine.engine_stats()
            ok = ok and stats["sync_quorum_serves"] >= 1
            ok = ok and psync.world_health()["dead_ranks"] == [2]
            health = suites[0].sync_health()
            ok = ok and health["degraded"] and health["degraded_tier"] == "quorum"

            # rank 2 restarts: journal restore + rejoin (next epoch); the
            # revived transport answers for the full world again. The
            # restart is a REPLACEMENT PROCESS: its in-memory program cache
            # starts empty (reset_engine), and only the persistent program
            # store — populated by the pre-kill world's compiles — stands
            # between its first post-rejoin compute and a recompile stall
            engine.reset_engine()
            restored = _suite()
            rejoin_info = restored.rejoin(rank2_path, rank=2)
            suites[2] = restored
            killed["dead"] = False
            ok = ok and rejoin_info["generation"] == 0
            ok = ok and psync.world_health()["dead_ranks"] == []

            # the survivors' recovery edge (steps=1) re-probes the FULL world
            for _, m in suites[0].items(keep_base=True, copy_state=False):
                m._computed = None
            compiles_before = engine.program_summary()["compiles"]
            got2 = {k: np.asarray(v) for k, v in suites[0].compute().items()}
            post_rejoin_compiles = engine.program_summary()["compiles"] - compiles_before
            post_rejoin_hits = int(engine.engine_stats()["progcache_hits"])
            ok = ok and all(_eq(got2[k], full_oracle[k]) for k in full_oracle)
            ok = ok and not suites[0].sync_health()["degraded"]
            # the certified invariant: no collective ever went out stale
            ok = ok and engine.engine_stats()["sync_stale_collectives"] == 0
            # ...and the revived world's first compute recompiled NOTHING:
            # every program it dispatched rehydrated from the persistent
            # store (counter-asserted — the zero-recompile rolling restart)
            ok = ok and post_rejoin_compiles == 0
            ok = ok and post_rejoin_hits > 0
        return {
            "scenario": "kill-rank-quorum-rejoin",
            "ok": bool(ok),
            "epoch": psync.world_epoch(),
            "post_rejoin_compiles": int(post_rejoin_compiles),
            "post_rejoin_progcache_hits": post_rejoin_hits,
        }
    finally:
        faults.set_recovery_policy(steps=8)
        progcache.configure(reset=True)
        psync.reset_membership()


def scenario_stale_epoch_collective() -> dict:
    """A membership change races a sync's retry: the epoch fence raises the
    classified EpochFault — the stale retry never reaches the transport,
    local state is bit-exact and retryable at the new epoch, and zero stale
    collectives are issued."""
    engine.reset_engine()
    psync.reset_membership()
    m = mt.MeanMetric()
    m.update(jnp.asarray([2.0, 4.0]))
    before = {k: np.asarray(v) for k, v in m.metric_state.items()}
    with _env(METRICS_TPU_SYNC_RETRIES="1"):
        calls = {"n": 0}

        def flaky(x):
            calls["n"] += 1
            if calls["n"] == 1:
                psync.bump_epoch("peer-died-mid-sync")  # membership change races the sync
                raise RuntimeError("transport reset by membership change")
            return x[None]

        bucketing._payload_allgather = flaky
        fenced = False
        try:
            m.sync(distributed_available=DIST_ON)
        except EpochFault:
            fenced = True  # classified, never a bare raise or a wrong-cohort pair
        stats = engine.engine_stats()
        ok = fenced and calls["n"] == 1  # the stale retry never re-issued
        ok = ok and stats["sync_epoch_fence_trips"] >= 1
        ok = ok and stats["sync_stale_collectives"] == 0
        after = {k: np.asarray(v) for k, v in m.metric_state.items()}
        ok = ok and all(_eq(after[k], before[k]) for k in before)
        ok = ok and not m._is_synced
        # re-entering at the current epoch succeeds
        m.sync(distributed_available=DIST_ON)
        m.unsync()
        ok = ok and _eq(m.compute(), np.asarray(3.0))
    return {"scenario": "stale-epoch-collective", "ok": bool(ok)}


def scenario_barrier_with_torn_generation() -> dict:
    """checkpoint_barrier journals at one agreed epoch-stamped step; the
    newest generation tears; rejoin demotes to the previous good generation
    (classified journal fault) and a survivor's handoff record fast-forwards
    the rejoiner to the barrier state bit-exactly."""
    engine.reset_engine()
    psync.reset_membership()
    d = tempfile.mkdtemp(prefix="mt-chaos-")
    path = os.path.join(d, "suite.journal")
    suite = _suite()
    suite.update(P, T)
    info1 = suite.checkpoint_barrier(path)
    suite.update(jnp.asarray(np.float32([5.0, 7.0])), jnp.asarray([1, 0]))
    info2 = suite.checkpoint_barrier(path)
    ok = info2["barrier_step"] > info1["barrier_step"] and info2["epoch"] >= info1["epoch"]
    manifest, _ = journal_mod.read_record(path)
    ok = ok and manifest["barrier_step"] == info2["barrier_step"]
    ok = ok and manifest["epoch"] == info2["epoch"]
    oracle = {k: np.asarray(v) for k, v in suite.compute().items()}
    # the survivor's retained copy of the newest barrier record
    survivor_record = journal_mod.pack_record(
        suite._journal_nodes(),
        manifest_extra={"epoch": info2["epoch"], "barrier_step": info2["barrier_step"]},
    )
    # tear the newest on-disk generation
    with open(path, "r+b") as fh:
        fh.seek(30)
        byte = fh.read(1)
        fh.seek(30)
        fh.write(bytes([byte[0] ^ 0xFF]))
    j0 = engine.engine_stats()["fault_journal"]
    restored = _suite()
    out = restored.rejoin(path, handoff=lambda meta: survivor_record, rank=0)
    ok = ok and out["generation"] == 1  # torn newest demoted, classified
    ok = ok and engine.engine_stats()["fault_journal"] > j0
    ok = ok and out["handoff"] is True  # the newer survivor record won
    ok = ok and out["restored_step"] == info2["barrier_step"]
    got = {k: np.asarray(v) for k, v in restored.compute().items()}
    ok = ok and all(_eq(got[k], oracle[k]) for k in oracle)
    psync.reset_membership()
    return {
        "scenario": "barrier-with-torn-generation",
        "ok": bool(ok),
        "demoted_to_generation": out["generation"],
    }


def scenario_force_deadline_degraded() -> dict:
    """ISSUE 13: the deadline fires at the FORCE of an in-flight async sync
    (the dispatcher thread is stuck in a hung collective): wait_with_deadline
    raises the classified SyncTimeoutFault, and METRICS_TPU_SYNC_DEGRADED=
    local serves the bit-exact local value through compute()'s auto-force —
    local state intact and retryable throughout, nothing applied."""
    engine.reset_engine()
    psync.reset_membership()
    faults.set_recovery_policy(steps=1)
    try:
        suite = _suite()
        suite.update(P, T)
        oracle = {k: np.asarray(v) for k, v in copy.deepcopy(suite).compute().items()}
        with _env(METRICS_TPU_SYNC_DEADLINE_MS="80", METRICS_TPU_SYNC_DEGRADED="local") as env:
            env.simulate_distributed()
            env.hang_transport(0.5)
            state_before = {
                k: {s: np.asarray(v) for s, v in m.metric_state.items()}
                for k, m in suite.items(keep_base=True, copy_state=False)
            }
            fut = suite.sync_async()
            ok = fut is not None and not fut.done()
            t0 = engine.engine_stats()["sync_deadline_timeouts"]
            # compute() auto-forces; the force deadline fires; the degraded
            # tier serves the local value instead of raising
            degraded_vals = {k: np.asarray(v) for k, v in suite.compute().items()}
            ok = ok and engine.engine_stats()["sync_deadline_timeouts"] > t0
            ok = ok and all(_eq(degraded_vals[k], oracle[k]) for k in oracle)
            ok = ok and suite.sync_health()["degraded"]
            for k, m in suite.items(keep_base=True, copy_state=False):
                ok = ok and not m._is_synced
                for s, v in m.metric_state.items():
                    ok = ok and _eq(np.asarray(v), state_before[k][s])
            # transport heals: the recovery edge re-probes and compute serves
            # the full coalesced sync again. The lane demoted TWICE (the
            # force failure, then the re-probe into the still-hung
            # transport), so its exponential backoff needs one extra clean
            # cycle before the edge fires.
            env.heal_transport()
            healed = {}
            for _ in range(3):
                for _, m in suite.items(keep_base=True, copy_state=False):
                    m._computed = None
                healed = {k: np.asarray(v) for k, v in suite.compute().items()}
                if not suite.sync_health()["degraded"]:
                    break
            ok = ok and all(_eq(healed[k], oracle[k]) for k in oracle)  # 1-proc gather = identity
            ok = ok and not suite.sync_health()["degraded"]
            ok = ok and engine.engine_stats()["sync_stale_collectives"] == 0
        return {"scenario": "force-deadline-degraded", "ok": bool(ok)}
    finally:
        faults.set_recovery_policy(steps=8)
        psync.reset_membership()


def scenario_membership_change_inflight() -> dict:
    """ISSUE 13: membership changes BETWEEN dispatch and force (a peer dies
    while the sync is in flight): the force's fence re-check classifies the
    stale future as EpochFault instead of pairing dead-world rows, local
    state is bit-exact and retryable at the new epoch, and zero stale-epoch
    collectives were issued (counter-asserted)."""
    engine.reset_engine()
    psync.reset_membership()
    m = mt.MeanMetric()
    m.update(jnp.asarray([2.0, 4.0]))
    before = {k: np.asarray(v) for k, v in m.metric_state.items()}
    with _env() as env:
        env.simulate_distributed()
        fut = m.sync_async()
        ok = fut is not None
        # the in-flight window: a peer is declared dead, the epoch bumps
        psync.set_expected_world(2)
        psync.mark_peer_dead(1, reason="chaos-inflight-kill")
        fenced = False
        try:
            fut.wait()
        except EpochFault:
            fenced = True  # classified, never a silent stale-row pair
        stats = engine.engine_stats()
        ok = ok and fenced
        ok = ok and stats["sync_epoch_fence_trips"] >= 1
        ok = ok and stats["sync_async_stale_futures"] >= 1
        ok = ok and stats["sync_stale_collectives"] == 0
        ok = ok and not m._is_synced
        after = {k: np.asarray(v) for k, v in m.metric_state.items()}
        ok = ok and all(_eq(after[k], before[k]) for k in before)
        # re-entering at the current epoch succeeds
        psync.reset_membership()
        m.sync()
        m.unsync()
        ok = ok and _eq(m.compute(), np.asarray(3.0))
    return {"scenario": "membership-change-inflight", "ok": bool(ok)}


def scenario_rank_dies_mid_window_close() -> dict:
    """A 3-rank world loses a rank mid ``Windowed.close_window()``: the
    epoch fence classifies the interrupted close as EpochFault (ring and
    live accumulator bit-intact — never a torn window), the survivors
    re-close at the new epoch, and the window value is bit-exact vs the
    uninterrupted fleet-level re-accumulation oracle."""
    engine.reset_engine()
    psync.reset_membership()
    from metrics_tpu import streaming

    with _env(METRICS_TPU_SYNC_RETRIES="1") as env:
        env.simulate_distributed()
        # 3 identical ranks: one stack covers both the close-id agreement
        # vector and the packed-state payload, and the fleet slot is
        # world * local by construction (integer-valued -> order-exact)
        world = {"n": 3}
        psync.set_expected_world(3)
        bucketing._host_allgather = lambda vec: np.stack([np.asarray(vec)] * world["n"])
        bucketing._payload_allgather = lambda x: jnp.stack([x] * world["n"])

        win = streaming.Windowed(mt.SumMetric(), window=4, stride=2, name="chaos-win")
        s1 = [jnp.asarray([1.0, 2.0]), jnp.asarray([3.0, 4.0])]
        s2 = [jnp.asarray([5.0, 6.0]), jnp.asarray([7.0, 8.0])]
        for x in s1:
            win.base.update(x)
        out1 = win.close_window(distributed_available=DIST_ON)
        ok = out1["world"] == 3 and _eq(out1["value"], np.float32(30.0))

        # stride 2 lands; rank 2 dies mid-close: the close-id agreement
        # exchange aborts AND the death bumps the epoch under the close
        for x in s2:
            win.base.update(x)
        live_before = np.asarray(win.base.compute())

        def dying(vec):
            psync.bump_epoch("rank-2-died-mid-window-close")
            raise RuntimeError("transport reset: rank died mid window close")

        bucketing._host_allgather = dying
        trips0 = engine.engine_stats()["window_epoch_trips"]
        fenced = False
        try:
            win.close_window(distributed_available=DIST_ON)
        except EpochFault:
            fenced = True  # classified, never a torn window
        ok = ok and fenced
        ok = ok and engine.engine_stats()["window_epoch_trips"] == trips0 + 1
        ok = ok and win.slots == 1 and win.window_id == 1  # ring intact
        ok = ok and _eq(np.asarray(win.base.compute()), live_before)

        # the survivors {0,1} re-close at the new epoch
        world["n"] = 2
        psync.set_expected_world(2)
        bucketing._host_allgather = lambda vec: np.stack([np.asarray(vec)] * world["n"])
        out2 = win.close_window(distributed_available=DIST_ON)
        ok = ok and out2["world"] == 2 and out2["epoch"] == psync.world_epoch()

        # uninterrupted oracle: the same fleet-level slots re-accumulated
        # from scratch (3 ranks closed slot 1, the 2 survivors slot 2);
        # sync_on_compute=False — the oracle already holds the fleet total
        oracle = mt.SumMetric(sync_on_compute=False)
        for _ in range(3):
            for x in s1:
                oracle.update(x)
        for _ in range(2):
            for x in s2:
                oracle.update(x)
        ok = ok and _eq(np.asarray(win.value()), np.asarray(oracle.compute()))
        ok = ok and engine.engine_stats()["sync_stale_collectives"] == 0
    return {
        "scenario": "rank-dies-mid-window-close",
        "ok": bool(ok),
        "epoch": psync.world_epoch(),
    }


def scenario_torn_window_ring_slot() -> dict:
    """A crashed ``Windowed`` restores its on-disk ring with the newest
    generation of one slot torn: the slot demotes to its previous good
    generation (classified journal fault, counted as a ring demotion), so
    the recovered window is the previous good window — re-accumulated only
    from records that verify."""
    engine.reset_engine()
    from metrics_tpu import streaming

    d = tempfile.mkdtemp(prefix="mt-chaos-")
    path = os.path.join(d, "win.journal")
    win = streaming.Windowed(
        mt.SumMetric(), window=4, stride=2, name="chaos-ring", journal_path=path
    )
    updates = [jnp.asarray([float(i), float(i) + 1.0]) for i in range(8)]
    for x in updates:
        win.update(x)  # 4 closes: ids 1..4 over a 2-slot ring
    ok = win.window_id == 4 and win.slots == 2
    # crash: the process state is gone; the newest generation of the
    # newest ring slot (close 4) is ALSO torn
    victim = win._slot_path(win.window_id % win._slots_cap)
    with open(victim, "r+b") as fh:
        fh.seek(30)
        byte = fh.read(1)
        fh.seek(30)
        fh.write(bytes([byte[0] ^ 0xFF]))
    j0 = engine.engine_stats()["fault_journal"]
    demo0 = engine.engine_stats()["window_ring_demotions"]
    fresh = streaming.Windowed(
        mt.SumMetric(), window=4, stride=2, name="chaos-ring-restored", journal_path=path
    )
    report = fresh.restore()
    ok = ok and engine.engine_stats()["window_ring_demotions"] == demo0 + 1
    ok = ok and engine.engine_stats()["fault_journal"] > j0
    # the torn slot demoted to its previous generation (close 2), so the
    # recovered window is the previous good window {closes 2, 3}
    oracle = mt.SumMetric()
    for x in updates[2:6]:
        oracle.update(x)
    ok = ok and report["slots"] == 2 and fresh.window_id == 3
    ok = ok and _eq(np.asarray(report["value"]), np.asarray(oracle.compute()))
    return {
        "scenario": "torn-window-ring-slot",
        "ok": bool(ok),
        "recovered_window": fresh.window_id,
    }


def _ingest_identity_exact() -> bool:
    """The settlement accounting identity, as a pure counter check (staging
    must be drained before calling): offered == admitted + coalesced + shed
    + quarantined, row-exact."""
    s = engine.engine_stats()
    return s["ingest_offered_rows"] == (
        s["ingest_admitted_rows"] + s["ingest_coalesced_rows"]
        + s["ingest_shed_rows"] + s["ingest_quarantined_rows"]
    )


def scenario_burst_arrival_shed() -> dict:
    """A 2x-overload burst at a bounded gateway: the watermark sheds exactly
    the excess (and is never exceeded mid-burst), the accounting identity is
    exact after the drain, and the admitted rows are bit-exact vs the oracle
    that saw only the admitted payloads."""
    engine.reset_engine()
    from metrics_tpu.ingest import IngestGateway

    arena = mt.MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="chaos-burst")
    oracle = mt.MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="chaos-burst-oracle")
    ids = np.asarray(arena.add(8))
    oracle.add(8)
    gw = IngestGateway(arena, name="chaos-burst", auto_flush=False, max_rows=64)
    rng = np.random.RandomState(7)
    admitted = shed = 0
    bounded = True
    for _ in range(16):  # 16 payloads x 8 rows = 128 offered at a 64-row watermark
        x = rng.rand(8, 2).astype(np.float32)
        out = gw.offer(x, tenant_ids=ids)
        if out["outcome"] == "staged":
            oracle.update(ids, x)
            admitted += out["rows"]
        else:
            shed += out["rows"]
        st = gw.state()
        bounded = bounded and st["staging_rows"] <= gw.max_rows
        bounded = bounded and st["staging_bytes"] <= gw.max_bytes
    gw.flush()
    s = engine.engine_stats()
    ok = bounded and admitted == 64 and shed == 64
    ok = ok and s["ingest_admitted_rows"] == admitted and s["ingest_shed_rows"] == shed
    ok = ok and _ingest_identity_exact()
    ok = ok and s["fault_ingest"] >= 1  # sheds route through the fault taxonomy
    ok = ok and _eq(arena.compute(list(ids)), oracle.compute(list(ids)))
    gw.close()
    return {
        "scenario": "burst-arrival-shed",
        "ok": bool(ok),
        "admitted_rows": admitted,
        "shed_rows": shed,
    }


def scenario_poison_payload_quarantine() -> dict:
    """A poison storm at the gateway door — schema mismatch, NaN storm, and
    an injected ``ingest-admit`` admission fault: every poison classifies
    into the bounded quarantine ring without a raise, and the target's
    state stays bit-intact."""
    engine.reset_engine()
    from metrics_tpu.ingest import IngestGateway

    arena = mt.MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="chaos-poison")
    ids = np.asarray(arena.add(4))
    gw = IngestGateway(arena, name="chaos-poison", auto_flush=False, quarantine_cap=4)
    rng = np.random.RandomState(11)
    gw.offer(rng.rand(4, 2).astype(np.float32), tenant_ids=ids)
    gw.flush()
    before = np.asarray(arena.compute(list(ids)))
    gw.offer(rng.rand(4, 3).astype(np.float32), tenant_ids=ids)  # schema mismatch
    gw.offer(np.full((4, 2), np.nan, np.float32), tenant_ids=ids)  # NaN storm
    with faults.inject_faults("ingest-admit") as plan:
        gw.offer(rng.rand(4, 2).astype(np.float32), tenant_ids=ids)
    gw.flush()
    s = engine.engine_stats()
    ok = plan.fired == 1
    ring = gw.quarantined()
    ok = ok and len(ring) == 3 and all("reason" in e and "error" in e for e in ring)
    ok = ok and s["ingest_quarantined_payloads"] == 3
    ok = ok and s["ingest_quarantined_rows"] == 12
    ok = ok and s["fault_ingest"] >= 3  # every poison classified, never raised
    ok = ok and _eq(np.asarray(arena.compute(list(ids))), before)  # target bit-intact
    ok = ok and _ingest_identity_exact()
    gw.close()
    return {
        "scenario": "poison-payload-quarantine",
        "ok": bool(ok),
        "quarantined": len(ring),
    }


def scenario_slow_consumer_backlog() -> dict:
    """A stalled consumer lets the backlog climb while the SLO budget plane
    fires: the gateway demotes to the degraded tier, coalesces same-schema
    load instead of growing the tail, sheds the rest; the woken consumer's
    drain absorbs an injected ``ingest-shed`` apply fault (that payload is
    quarantined, the drain continues) and the clean follow-up flush walks
    the recovery edge back to the normal tier — accounting exact throughout."""
    engine.reset_engine()
    from metrics_tpu.ingest import IngestGateway
    from metrics_tpu.ops import telemetry as telemetry_mod

    arena = mt.MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="chaos-backlog")
    oracle = mt.MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="chaos-backlog-oracle")
    ids = np.asarray(arena.add(8))
    oracle.add(8)
    gw = IngestGateway(arena, name="chaos-backlog", auto_flush=False, max_rows=32)
    rng = np.random.RandomState(13)
    x = lambda: rng.rand(8, 2).astype(np.float32)  # noqa: E731
    faults.set_recovery_policy(steps=1)
    try:
        a = x()
        gw.offer(a, tenant_ids=ids)  # healthy consumer: admitted cleanly
        gw.flush()
        oracle.update(ids, a)
        ok = not gw.degraded
        ok = ok and gw.offer(x(), tenant_ids=ids)["outcome"] == "staged"  # consumer stalls
        # the SLO budget plane reports a new violation while the backlog sits
        telemetry_mod._slo_violations["engine-flush"] = (
            telemetry_mod._slo_violations.get("engine-flush", 0) + 1
        )
        # degraded tier (watermark 32 * 0.5 = 16): same-schema load coalesces
        # into the staged payload instead of growing the tail...
        ok = ok and gw.offer(x(), tenant_ids=ids)["outcome"] == "coalesced"
        ok = ok and gw.degraded
        # ...and load past the shrunk watermark is shed, never queued
        ok = ok and gw.offer(x(), tenant_ids=ids)["outcome"] == "shed"
        ok = ok and gw.state()["staging_rows"] == 16
        # the consumer wakes into an apply fault mid-drain: the poisoned
        # payload quarantines (classified), the drain does not raise
        with faults.inject_faults("ingest-shed") as plan:
            gw.flush()
        s = engine.engine_stats()
        ok = ok and plan.fired == 1
        ok = ok and s["ingest_apply_faults"] == 1 and s["ingest_quarantined_rows"] == 16
        ok = ok and gw.degraded  # a faulted drain is not a recovery edge
        e = x()
        ok = ok and gw.offer(e, tenant_ids=ids)["outcome"] == "staged"
        gw.flush()  # clean drain, no new violations: the standard recovery edge
        oracle.update(ids, e)
        ok = ok and not gw.degraded
        ok = ok and s["ingest_degraded_offers"] >= 2
        ok = ok and _ingest_identity_exact()
        ok = ok and _eq(arena.compute(list(ids)), oracle.compute(list(ids)))
    finally:
        faults.set_recovery_policy(steps=8)
        gw.close()
    return {
        "scenario": "slow-consumer-backlog",
        "ok": bool(ok),
        "quarantined_rows": int(engine.engine_stats()["ingest_quarantined_rows"]),
        "recovered": not gw.degraded,
    }


FAST = [
    scenario_timeout_then_compile,
    scenario_crash_with_torn_journal,
    scenario_pack_then_gather,
    scenario_kill_rank_quorum_rejoin,
    scenario_stale_epoch_collective,
    scenario_force_deadline_degraded,
    scenario_membership_change_inflight,
    scenario_barrier_with_torn_generation,
    scenario_rank_dies_mid_window_close,
    scenario_torn_window_ring_slot,
    scenario_burst_arrival_shed,
    scenario_poison_payload_quarantine,
    scenario_slow_consumer_backlog,
]
FULL = FAST + [scenario_flush_fault_during_journal_save]


def main(argv) -> int:
    fast = "--fast" in argv
    failures = 0
    for scenario in FAST if fast else FULL:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # degradation warnings are the point
            try:
                result = scenario()
            except Exception as exc:  # noqa: BLE001 — a scenario crash IS a violation
                result = {
                    "scenario": scenario.__name__,
                    "ok": False,
                    "error": f"{type(exc).__name__}: {exc}",
                }
        failures += 0 if result["ok"] else 1
        print(json.dumps(result))
    print(
        json.dumps(
            {
                "summary": "chaos_sweep",
                "scenarios": len(FAST if fast else FULL),
                "failures": failures,
                "invariant": "bit-exact result or classified raise, never silent corruption",
            }
        )
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
