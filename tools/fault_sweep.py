"""Fault-injection sweep: every named site x a representative metric set.

The ``make faults`` entry point. For each injection site (``probe``,
``compile``, ``flush-chunk-0``, ``flush-chunk-1``, ``donation``,
``sync-gather``, ``sync-pack``, ``host-offload``, ``journal-write``,
``journal-load``) it drives a representative workload under
``metrics_tpu.ops.faults.inject_faults`` and asserts:

- the final metric values are BIT-EXACT against a step-by-step eager oracle
  (fresh instance, deferral off, no tolerance widening);
- the plan actually fired (the site is really on the exercised path);
- for recoverable domains, the degradation ladder re-promoted the owner
  (``engine_stats`` shows the demotion AND the promotion).

Prints one JSON line per site plus a summary; exits non-zero on any
mismatch. Runs on CPU by default so results are deterministic anywhere
(override with JAX_PLATFORMS).
"""
from __future__ import annotations

import json
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("METRICS_TPU_VALIDATION", "first")
os.environ.setdefault("METRICS_TPU_SYNC_BACKOFF_MS", "0")

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_DIR not in sys.path:
    sys.path.insert(0, _REPO_DIR)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import metrics_tpu as mt  # noqa: E402
from metrics_tpu.ops import engine, faults  # noqa: E402
from metrics_tpu.utils.exceptions import SyncFault  # noqa: E402

RNG = np.random.RandomState(0)
A = jnp.asarray(RNG.rand(32).astype(np.float32))
P = jnp.asarray(RNG.rand(64).astype(np.float32))
T = jnp.asarray(RNG.randint(0, 2, 64))
N_STEPS = 8


def _tree_equal(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and np.array_equal(a, b)


def _oracle_mean(n: int):
    engine.set_deferred_dispatch(False)
    try:
        e = mt.MeanMetric()
        for _ in range(n):
            e.update(A)
        return np.asarray(e.compute())
    finally:
        engine.set_deferred_dispatch(True)


def _oracle_accuracy(n: int):
    engine.set_deferred_dispatch(False)
    try:
        e = mt.Accuracy()
        vals = [np.asarray(e(P, T)) for _ in range(n)]
        return vals, np.asarray(e.compute())
    finally:
        engine.set_deferred_dispatch(True)


def _scenario_update_queue(site: str):
    """N deferred updates with the fault armed mid-stream; the flush (or its
    eager replay) must land bit-exactly on the oracle."""
    engine.set_deferred_dispatch(True)
    m = mt.MeanMetric()
    m.update(A)
    with faults.inject_faults(site) as plan:
        for _ in range(N_STEPS - 1):
            m.update(A)
        value = np.asarray(m.compute())
    return _tree_equal(value, _oracle_mean(N_STEPS)), plan.fired


def _scenario_per_call(site: str):
    """Per-call fused forwards (deferral off) with the fault at step 3; the
    per-step values AND the final value must match the oracle."""
    engine.set_deferred_dispatch(False)
    try:
        if site == "compile":
            engine.reset_engine()  # the compile site fires on cache misses
        m = mt.Accuracy()
        got = [np.asarray(m(P, T))]  # first signature call: eager, validated
        # arm across steps 2-3: the compile site fires at program BUILD
        # (step 2, a cache miss), the donation site at donated execution
        with faults.inject_faults(site) as plan:
            got.append(np.asarray(m(P, T)))
            got.append(np.asarray(m(P, T)))
        for _ in range(N_STEPS - 3):
            got.append(np.asarray(m(P, T)))
        final = np.asarray(m.compute())
    finally:
        engine.set_deferred_dispatch(True)
    vals, oracle_final = _oracle_accuracy(N_STEPS)
    ok = _tree_equal(final, oracle_final) and all(
        _tree_equal(g, v) for g, v in zip(got, vals)
    )
    # recoverable domains must show the recovery edge in the ladder
    stats = engine.engine_stats()
    if site in ("compile", "donation"):
        ok = ok and stats["fault_promotions"] >= 1 and stats["fault_demotions"] >= 1
    return ok, plan.fired


def _scenario_sync(site: str):
    m = mt.MeanMetric()
    m.update(jnp.asarray([2.0, 4.0]))
    raised = False
    with faults.inject_faults(site, count=100) as plan:
        try:
            m.sync(distributed_available=lambda: True)
        except SyncFault:
            raised = True
    # failed sync: local state intact and retryable
    m.sync(distributed_available=lambda: True)
    m.unsync()
    return raised and _tree_equal(m.compute(), np.asarray(3.0)), plan.fired


def _scenario_sync_pack(site: str):
    """Injected pack failure on a suite sync: the coalesced engine must
    demote to the member-wise per-state protocol BIT-EXACTLY (no error
    surfaces, local state intact), and the ladder must re-promote after the
    clean-sync recovery edge (demote -> per-state -> coalesced again)."""
    coll = mt.MetricCollection({"mean": mt.MeanMetric(), "mse": mt.MeanSquaredError()})
    coll.update(A, A)
    oracle = {k: np.asarray(v) for k, v in coll.compute().items()}
    with faults.inject_faults(site) as plan:
        coll.sync(distributed_available=lambda: True)  # falls back, no raise
    coll.unsync()
    ok = all(_tree_equal(np.asarray(v), oracle[k]) for k, v in coll.compute().items())
    lad = coll.__dict__["_fault_ladders"]["sync-pack"]
    ok = ok and lad.demoted
    # clean member-wise syncs advance the recovery edge (policy steps=2)
    for _ in range(2):
        coll.sync(distributed_available=lambda: True)
        coll.unsync()
    ok = ok and not lad.demoted
    # re-promoted: the suite coalesces again (one payload collective)
    s0 = engine.engine_stats()["sync_coalesced_payloads"]
    coll.sync(distributed_available=lambda: True)
    coll.unsync()
    ok = ok and engine.engine_stats()["sync_coalesced_payloads"] == s0 + 1
    ok = ok and all(_tree_equal(np.asarray(v), oracle[k]) for k, v in coll.compute().items())
    stats = engine.engine_stats()
    ok = ok and stats["fault_demotions"] >= 1 and stats["fault_promotions"] >= 1
    return ok, plan.fired


def _scenario_journal_write(site: str):
    """Injected write failure while auto-journaling a suite: updates must
    keep running (journal lane demotes, warn once), the on-disk ring must
    stay intact (the PREVIOUS record still loads), and the recovery edge
    must re-enable journaling (a later save lands)."""
    import tempfile

    d = tempfile.mkdtemp(prefix="mt-fault-sweep-")
    path = os.path.join(d, "suite.journal")

    def make():
        return mt.MetricCollection({"mean": mt.MeanMetric(), "mse": mt.MeanSquaredError()})

    coll = make()
    coll.journal(path, every_n=1)
    coll.update(A, A)  # good generation on disk
    oracle1 = {k: np.asarray(v) for k, v in coll.compute().items()}
    with faults.inject_faults(site) as plan:
        coll.update(A, A)  # write fails -> journal lane demotes, no raise
    lad = coll.__dict__["_fault_ladders"]["journal"]
    ok = lad.demoted
    # the ring survived: the pre-fault record restores the 1-update state
    fresh = make()
    fresh.load_state(path)
    ok = ok and all(_tree_equal(v, oracle1[k]) for k, v in fresh.compute().items())
    # clean observed steps advance the edge (policy steps=2, deferred updates
    # credit at flush); journaling resumes after the re-arm
    for _ in range(2):
        coll.update(A, A)
        coll.compute()
    ok = ok and not lad.demoted
    coll.update(A, A)  # journals again: all 5 updates on disk now
    final = {k: np.asarray(v) for k, v in coll.compute().items()}
    fresh2 = make()
    fresh2.load_state(path)
    ok = ok and all(_tree_equal(v, final[k]) for k, v in fresh2.compute().items())
    return ok, plan.fired


def _scenario_journal_load(site: str):
    """Injected load failure on the newest generation: restore must demote to
    the previous good generation (classified journal fault, no raise) and be
    bit-exact vs that generation's oracle."""
    import tempfile

    d = tempfile.mkdtemp(prefix="mt-fault-sweep-")
    path = os.path.join(d, "m.journal")
    m = mt.MeanMetric()
    m.update(A)
    m.save_state(path)  # generation to demote to
    m.update(A)
    m.save_state(path)  # newest generation (its read will be failed)
    fresh = mt.MeanMetric()
    with faults.inject_faults(site) as plan:
        gen = fresh.load_state(path)
    ok = gen == 1 and _tree_equal(fresh.compute(), _oracle_mean(1))
    ok = ok and engine.engine_stats()["fault_journal"] >= 1
    # uninjected load lands on the newest generation, bit-exact
    fresh2 = mt.MeanMetric()
    ok = ok and fresh2.load_state(path) == 0 and _tree_equal(fresh2.compute(), _oracle_mean(2))
    return ok, plan.fired


def _scenario_progcache_store(site: str):
    """Injected store failure while a freshly compiled program is exported to
    the persistent cache: the compute itself stays bit-exact (a store failure
    never surfaces to the caller), the failure classifies through the journal
    domain, and no partial entry lands in the store."""
    import tempfile

    from metrics_tpu.ops import progcache

    d = tempfile.mkdtemp(prefix="mt-fault-sweep-")
    progcache.configure(reset=True)
    progcache.configure(enabled=True, cache_dir=d)
    engine.set_deferred_dispatch(False)
    try:
        m = mt.MeanMetric()
        with faults.inject_faults(site, count=100) as plan:
            for _ in range(N_STEPS):
                m.update(A)
            value = np.asarray(m.compute())
        stats = engine.engine_stats()
        ok = _tree_equal(value, _oracle_mean(N_STEPS))
        ok = ok and stats["fault_journal"] >= 1
        ok = ok and stats["progcache_stores"] == 0
    finally:
        engine.set_deferred_dispatch(True)
        progcache.configure(reset=True)
    return ok, plan.fired


def _scenario_progcache_load(site: str):
    """Warm-boot load failure: a stored entry's read fails classified mid-
    rehydration; the replacement process demotes to a fresh compile with
    bit-exact values (never a wrong program), and a later uninjected boot
    rehydrates from the intact store."""
    import tempfile

    from metrics_tpu.ops import progcache

    d = tempfile.mkdtemp(prefix="mt-fault-sweep-")
    progcache.configure(reset=True)
    progcache.configure(enabled=True, cache_dir=d)
    engine.set_deferred_dispatch(False)
    try:
        warm = mt.MeanMetric()
        for _ in range(N_STEPS):
            warm.update(A)
        np.asarray(warm.compute())  # populate the store
        ok = engine.engine_stats()["progcache_stores"] >= 1
        # replacement process: empty in-memory cache, loads injected to fail
        engine.reset_engine()
        with faults.inject_faults(site, count=100) as plan:
            m = mt.MeanMetric()
            for _ in range(N_STEPS):
                m.update(A)
            value = np.asarray(m.compute())
        ok = ok and _tree_equal(value, _oracle_mean(N_STEPS))
        ok = ok and engine.engine_stats()["fault_journal"] >= 1
        # uninjected boot: the store was never corrupted — entries rehydrate
        engine.reset_engine()
        progcache.configure(reset=True)  # clear the demoted store lane
        progcache.configure(enabled=True, cache_dir=d)
        hits0 = engine.engine_stats()["progcache_hits"]
        fresh = mt.MeanMetric()
        for _ in range(N_STEPS):
            fresh.update(A)
        ok = ok and _tree_equal(np.asarray(fresh.compute()), _oracle_mean(N_STEPS))
        ok = ok and engine.engine_stats()["progcache_hits"] > hits0
    finally:
        engine.set_deferred_dispatch(True)
        progcache.configure(reset=True)
    return ok, plan.fired


def _scenario_host_offload(site: str):
    rows = jnp.asarray([1.0, 2.0])
    c = mt.CatMetric(compute_on_cpu=True)
    c.update(rows)
    with faults.inject_faults(site) as plan:
        c.update(rows)
    for _ in range(N_STEPS - 2):
        c.update(rows)
    e = mt.CatMetric()
    for _ in range(N_STEPS):
        e.update(rows)
    return _tree_equal(c.compute(), np.asarray(e.compute())), plan.fired


SWEEP = {
    "probe": _scenario_update_queue,
    "compile": _scenario_per_call,
    "flush-chunk-0": _scenario_update_queue,
    "flush-chunk-1": _scenario_update_queue,
    "donation": _scenario_per_call,
    "sync-gather": _scenario_sync,
    "sync-pack": _scenario_sync_pack,
    "host-offload": _scenario_host_offload,
    "journal-write": _scenario_journal_write,
    "journal-load": _scenario_journal_load,
    "progcache-store": _scenario_progcache_store,
    "progcache-load": _scenario_progcache_load,
}

# Site families exercised by tools/chaos_sweep.py instead: a fence trip needs
# a scripted membership race (epoch bump mid-protocol), and the ingest
# gateway's admission/shed sites need the multi-step overload scenarios
# (burst-arrival-shed, poison-payload-quarantine) — not one-site sweeps.
CHAOS_COVERED = frozenset({"epoch-fence", "ingest-admit", "ingest-shed"})


def _coverage_gaps():
    """Every family in the canonical registry (``faults.FAULT_SITES`` — the
    same tuple the linter and the docs drift check consume) must be exercised
    here or in the chaos sweep; a new site without a scenario fails the
    ``make faults`` stage instead of silently never firing."""
    from tools.invlint.registry import site_family

    swept = {site_family(site) for site in SWEEP}
    return sorted(set(faults.FAULT_SITES) - swept - CHAOS_COVERED)


def main() -> int:
    gaps = _coverage_gaps()
    if gaps:
        print(json.dumps({"summary": "fault_sweep", "uncovered_sites": gaps}))
        print(
            f"fault_sweep: {len(gaps)} registered injection site(s) have no sweep"
            f" scenario: {gaps} — add one here or declare it in CHAOS_COVERED"
            " with a chaos_sweep scenario",
            file=sys.stderr,
        )
        return 1
    faults.set_recovery_policy(steps=2)
    failures = 0
    results = {}
    for site, scenario in SWEEP.items():
        engine.reset_engine()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # fallback warnings are expected here
            ok, fired = scenario(site)
        if fired == 0:
            ok = False  # the site was never reached: the sweep is lying
        results[site] = {"bit_exact": bool(ok), "fired": int(fired)}
        failures += 0 if ok else 1
        print(json.dumps({"site": site, **results[site]}))
    stats = engine.engine_stats()
    print(
        json.dumps(
            {
                "summary": "fault_sweep",
                "sites": len(SWEEP),
                "failures": failures,
                "fault_counters": {
                    k: v for k, v in stats.items() if k.startswith("fault_") and v
                },
            }
        )
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
