"""Offline tooling for metrics_tpu (benches, sweeps, docs checks, linters).

Package marker so `python -m tools.invlint` resolves from the repo root; the
standalone scripts in this directory keep working unchanged.
"""
