"""COCO-scale MeanAveragePrecision wall-clock: ours vs the mounted reference.

VERDICT #6 gate: >= 5k detections on identical data, compute() wall-clock
must be <= the reference CPU path. Run:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/bench_map.py [--images 500]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def make_dataset(n_images: int, n_classes: int = 20, seed: int = 0):
    """Realistic detection batches: ~10 dets & ~7 gts per image."""
    rng = np.random.RandomState(seed)
    batches = []
    for _ in range(n_images):
        n_det = rng.randint(6, 15)
        n_gt = rng.randint(4, 10)
        gxy = rng.rand(n_gt, 2) * 500
        gwh = 20 + rng.rand(n_gt, 2) * 120
        gboxes = np.concatenate([gxy, gxy + gwh], 1).astype(np.float32)
        glabels = rng.randint(0, n_classes, n_gt)
        # detections: jittered copies of gts + noise boxes
        idx = rng.randint(0, n_gt, n_det)
        noise = rng.randn(n_det, 4).astype(np.float32) * 8
        dboxes = gboxes[idx] + noise
        dboxes[:, 2:] = np.maximum(dboxes[:, 2:], dboxes[:, :2] + 1)
        dlabels = np.where(rng.rand(n_det) < 0.85, glabels[idx], rng.randint(0, n_classes, n_det))
        scores = rng.rand(n_det).astype(np.float32)
        batches.append(
            (
                dict(boxes=dboxes, scores=scores, labels=dlabels.astype(np.int64)),
                dict(boxes=gboxes, labels=glabels.astype(np.int64)),
            )
        )
    return batches


def bench_ours(batches):
    import metrics_tpu as mt

    metric = mt.MeanAveragePrecision()
    t0 = time.perf_counter()
    for det, gt in batches:
        # host numpy passes through AS-IS (update stores it without any
        # host->device transfer; compute materializes in bulk) — the same
        # host-resident inputs the reference receives. Wrapping each image in
        # jnp.asarray would TIME 5 tunnel transfers per image instead of the
        # metric (22 ms/image measured) — a detector running on device hands
        # over device arrays, which ride the zero-sync append path instead.
        metric.update(
            [dict(boxes=det["boxes"], scores=det["scores"], labels=det["labels"])],
            [dict(boxes=gt["boxes"], labels=gt["labels"])],
        )
    t_update = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = metric.compute()
    t_compute = time.perf_counter() - t0
    return float(out["map"]), t_update, t_compute


def _install_torchvision_shim():
    """Minimal torch implementations of the three torchvision box ops the
    reference mAP uses (torchvision is not installed here; these are the
    standard published formulas, xyxy convention)."""
    import types

    import torch

    def box_area(boxes):
        return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])

    def box_iou(boxes1, boxes2):
        area1, area2 = box_area(boxes1), box_area(boxes2)
        lt = torch.max(boxes1[:, None, :2], boxes2[None, :, :2])
        rb = torch.min(boxes1[:, None, 2:], boxes2[None, :, 2:])
        wh = (rb - lt).clamp(min=0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)

    def box_convert(boxes, in_fmt, out_fmt):
        if in_fmt == out_fmt:
            return boxes
        if in_fmt == "xywh" and out_fmt == "xyxy":
            x, y, w, h = boxes.unbind(-1)
            return torch.stack([x, y, x + w, y + h], dim=-1)
        if in_fmt == "cxcywh" and out_fmt == "xyxy":
            cx, cy, w, h = boxes.unbind(-1)
            return torch.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], dim=-1)
        if in_fmt == "xyxy" and out_fmt == "xywh":
            x1, y1, x2, y2 = boxes.unbind(-1)
            return torch.stack([x1, y1, x2 - x1, y2 - y1], dim=-1)
        raise ValueError(f"unsupported conversion {in_fmt}->{out_fmt}")

    tv = types.ModuleType("torchvision")
    tv.__version__ = "0.15.0"
    ops = types.ModuleType("torchvision.ops")
    ops.box_area, ops.box_iou, ops.box_convert = box_area, box_iou, box_convert
    tv.ops = ops
    sys.modules["torchvision"] = tv
    sys.modules["torchvision.ops"] = ops


def bench_reference(batches):
    from tests.helpers.reference_oracle import get_reference

    ref = get_reference()
    if ref is None:
        return None
    import torch

    _install_torchvision_shim()
    import torchmetrics.detection.mean_ap as ref_map_mod
    import torchvision.ops as tv_ops

    ref_map_mod._TORCHVISION_GREATER_EQUAL_0_8 = True
    ref_map_mod.box_area = tv_ops.box_area
    ref_map_mod.box_iou = tv_ops.box_iou
    ref_map_mod.box_convert = tv_ops.box_convert

    metric = ref_map_mod.MeanAveragePrecision()
    t0 = time.perf_counter()
    for det, gt in batches:
        metric.update(
            [dict(boxes=torch.from_numpy(det["boxes"]), scores=torch.from_numpy(det["scores"]), labels=torch.from_numpy(det["labels"]))],
            [dict(boxes=torch.from_numpy(gt["boxes"]), labels=torch.from_numpy(gt["labels"]))],
        )
    t_update = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = metric.compute()
    t_compute = time.perf_counter() - t0
    return float(out["map"]), t_update, t_compute


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--images", type=int, default=500)
    parser.add_argument("--skip-reference", action="store_true")
    args = parser.parse_args()

    batches = make_dataset(args.images)
    n_det = sum(len(b[0]["scores"]) for b in batches)
    print(f"{args.images} images, {n_det} detections")

    ours = bench_ours(batches)
    print(f"ours:      map={ours[0]:.4f}  update={ours[1]:.2f}s  compute={ours[2]:.2f}s")
    if not args.skip_reference:
        theirs = bench_reference(batches)
        if theirs is None:
            print("reference: unavailable")
        else:
            print(f"reference: map={theirs[0]:.4f}  update={theirs[1]:.2f}s  compute={theirs[2]:.2f}s")
            print(f"compute speedup vs reference: {theirs[2] / ours[2]:.2f}x")


if __name__ == "__main__":
    main()
