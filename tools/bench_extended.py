"""Extended wall-clock benchmarks for the BASELINE.md north-star targets.

`bench.py` prints the single headline JSON line the driver records; this tool
measures the heavyweight end-to-end paths the baseline table calls out — COCO
mAP, FID (Inception features + on-device sqrtm), retrieval, and the native
text kernels — one JSON line each. The reference cannot run its counterparts
in this environment (its mAP needs torchvision, FID needs torch-fidelity,
segm needs pycocotools — none installed), so these are absolute numbers for
our implementation; where a same-host comparison IS possible (pure-python
reference paths), `vs` reports the speedup.

    python tools/bench_extended.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, warmup: int = 1, trials: int = 3) -> float:
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - start)
    return best


def bench_map() -> dict:
    """COCO-style mAP: 25 images, ~30 detections / ~20 GT boxes each."""
    from metrics_tpu.detection import MeanAveragePrecision

    rng = np.random.RandomState(0)
    n_images = 25
    preds, targets = [], []
    for _ in range(n_images):
        nd, ng = rng.randint(20, 40), rng.randint(10, 30)
        xy = rng.rand(nd, 2) * 400
        wh = rng.rand(nd, 2) * 80 + 4
        preds.append(
            {
                "boxes": np.concatenate([xy, xy + wh], 1).astype(np.float32),
                "scores": rng.rand(nd).astype(np.float32),
                "labels": rng.randint(0, 5, nd),
            }
        )
        xy = rng.rand(ng, 2) * 400
        wh = rng.rand(ng, 2) * 80 + 4
        targets.append(
            {
                "boxes": np.concatenate([xy, xy + wh], 1).astype(np.float32),
                "labels": rng.randint(0, 5, ng),
            }
        )

    def run():
        m = MeanAveragePrecision()
        m.update(preds, targets)
        m.compute()

    secs = _time(run)
    return {"metric": "coco_map_25img_wallclock", "value": round(secs, 3), "unit": "s"}


def bench_fid() -> dict:
    """FID over 2x64 images at 299x299: Inception features + f64 sqrtm."""
    from metrics_tpu.image import FrechetInceptionDistance

    rng = np.random.RandomState(0)
    real = rng.randint(0, 255, (32, 3, 299, 299), dtype=np.uint8)
    fake = rng.randint(0, 255, (32, 3, 299, 299), dtype=np.uint8)

    def run():
        fid = FrechetInceptionDistance(feature=2048, allow_random_weights=True)
        for i in range(2):
            fid.update(real, real=True)
            fid.update(fake, real=False)
        fid.compute()

    secs = _time(run, warmup=1, trials=2)
    return {"metric": "fid_128img_wallclock", "value": round(secs, 3), "unit": "s"}


def bench_retrieval() -> dict:
    """MAP over 500 queries x 20 docs — one device program regardless of query count."""
    import jax.numpy as jnp

    from metrics_tpu.retrieval import RetrievalMAP

    rng = np.random.RandomState(0)
    nq, per_q = 500, 20
    n = nq * per_q
    indexes = jnp.asarray(np.repeat(np.arange(nq), per_q))
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    target = jnp.asarray((rng.rand(n) > 0.7).astype(np.int32))

    def run():
        m = RetrievalMAP()
        m.update(preds, target, indexes)
        float(m.compute())

    secs = _time(run)
    return {
        "metric": "retrieval_map_500q_wallclock",
        "value": round(secs, 3),
        "unit": "s",
    }


def bench_native_text() -> dict:
    """2000-token edit distance: native C++ vs the pure-python DP."""
    from metrics_tpu import native

    rng = np.random.RandomState(0)
    a = rng.randint(0, 50, 2000).astype(np.int32)
    b = rng.randint(0, 50, 2000).astype(np.int32)
    if not native.available():
        return {"metric": "native_edit_distance_2000tok", "value": 0.0, "unit": "s", "note": "no toolchain"}
    t_native = _time(lambda: native.levenshtein(a, b))
    os.environ["METRICS_TPU_NO_NATIVE"] = "1"
    try:
        t_py = _time(lambda: native.levenshtein_fallback(a, b), warmup=0, trials=1) if hasattr(native, "levenshtein_fallback") else None
    finally:
        os.environ.pop("METRICS_TPU_NO_NATIVE", None)
    out = {"metric": "native_edit_distance_2000tok", "value": round(t_native * 1e3, 3), "unit": "ms"}
    if t_py:
        out["vs"] = round(t_py / t_native, 1)
    return out


def bench_map_scale(n_images: int = 500) -> dict:
    """COCO-scale mAP (>=500 images / ~5k+ detections) vs the reference on
    identical data — the committed artifact behind docs/performance.md's
    COCO-scale table (VERDICT r2 #7)."""
    from tools.bench_map import bench_ours, bench_reference, make_dataset

    batches = make_dataset(n_images)
    n_det = sum(len(b[0]["scores"]) for b in batches)
    ours_map, ours_upd, ours_cmp = bench_ours(batches)
    out = {
        "metric": f"coco_map_{n_images}img_scale",
        "n_detections": n_det,
        "ours_update_s": round(ours_upd, 2),
        "ours_compute_s": round(ours_cmp, 2),
        "map": round(ours_map, 4),
    }
    try:
        ref = bench_reference(batches)
    except Exception as err:  # keep the measured ours-side numbers
        out["ref_error"] = str(err)[:120]
        ref = None
    if ref is not None:
        ref_map, ref_upd, ref_cmp = ref
        out.update(
            ref_update_s=round(ref_upd, 2),
            ref_compute_s=round(ref_cmp, 2),
            ref_map=round(ref_map, 4),
            compute_speedup=round(ref_cmp / ours_cmp, 2),
            cycle_speedup=round((ref_upd + ref_cmp) / (ours_upd + ours_cmp), 2),
        )
    return out


def bench_fid_scale(n_images: int = 1024, batch: int = 64) -> dict:
    """FID at >=1k images per side (random weights — wall-clock only) vs the
    torch-CPU architecture mirror on identical data (VERDICT r2 #7)."""
    import jax.numpy as jnp

    from metrics_tpu.image import FrechetInceptionDistance

    rng = np.random.RandomState(0)
    n_batches = n_images // batch
    n_images = n_batches * batch  # label the workload actually processed
    real = [rng.randint(0, 256, (batch, 3, 299, 299), dtype=np.uint8) for _ in range(n_batches)]
    fake = [rng.randint(0, 256, (batch, 3, 299, 299), dtype=np.uint8) for _ in range(n_batches)]

    fid = FrechetInceptionDistance(feature=2048, allow_random_weights=True)
    fid.update(jnp.asarray(real[0]), real=True)  # compile warmup
    fid.reset()
    start = time.perf_counter()
    for r, f in zip(real, fake):
        fid.update(jnp.asarray(r), real=True)
        fid.update(jnp.asarray(f), real=False)
    t_update = time.perf_counter() - start
    start = time.perf_counter()
    ours_val = float(fid.compute())
    t_compute = time.perf_counter() - start
    out = {
        "metric": f"fid_{2 * n_images}img_scale",
        "ours_update_s": round(t_update, 2),
        "ours_compute_s": round(t_compute, 2),
        "fid": round(ours_val, 4),
    }

    try:
        import torch

        from tests.helpers.torch_mirrors import TorchInceptionMirror, randomize_inception_

        mirror = TorchInceptionMirror()
        randomize_inception_(mirror)
        start = time.perf_counter()
        feats = {"real": [], "fake": []}
        with torch.no_grad():
            for r, f in zip(real, fake):
                feats["real"].append(mirror(torch.from_numpy(r).float() / 255.0 * 2.0 - 1.0)["2048"].numpy())
                feats["fake"].append(mirror(torch.from_numpy(f).float() / 255.0 * 2.0 - 1.0)["2048"].numpy())
        ref_update = time.perf_counter() - start
        import scipy.linalg

        start = time.perf_counter()
        rr = np.concatenate(feats["real"]).astype(np.float64)
        ff = np.concatenate(feats["fake"]).astype(np.float64)
        mu1, mu2 = rr.mean(0), ff.mean(0)
        cov1, cov2 = np.cov(rr, rowvar=False), np.cov(ff, rowvar=False)
        covmean = scipy.linalg.sqrtm(cov1 @ cov2)
        if np.iscomplexobj(covmean):
            covmean = covmean.real
        _ = float((mu1 - mu2) @ (mu1 - mu2) + np.trace(cov1) + np.trace(cov2) - 2 * np.trace(covmean))
        ref_compute = time.perf_counter() - start
        out.update(
            ref_update_s=round(ref_update, 2),
            ref_compute_s=round(ref_compute, 2),
            cycle_speedup=round((ref_update + ref_compute) / (t_update + t_compute), 2),
        )
    except Exception as err:
        out["ref_error"] = str(err)[:120]
    return out


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--scale", action="store_true", help="run the COCO/FID-scale workloads too")
    args = parser.parse_args()
    benches = [bench_retrieval, bench_map, bench_native_text, bench_fid]
    if args.scale:
        benches += [bench_map_scale, bench_fid_scale]
    for fn in benches:
        try:
            print(json.dumps(fn()))
        except Exception as err:  # keep the other benches running
            print(json.dumps({"metric": fn.__name__, "error": str(err)[:200]}))


if __name__ == "__main__":
    main()
