"""Convert ``lpips``-package checkpoints to the metrics_tpu flat-npz format.

Usage:
    python tools/convert_lpips_weights.py alex full_lpips_state.pth out.npz
    # then: LearnedPerceptualImagePatchSimilarity(net_type="alex",
    #           params=params_from_npz("out.npz"))

The source is the state dict of ``lpips.LPIPS(net=...)`` (the exact network
the reference wraps — `image/lpip.py:24-40`): backbone convs under
``net.slice{k}.{idx}.*`` (torchvision ``features`` indices preserved inside
each slice) and the learned 1x1 heads under ``lin{k}.model.1.weight``.
Backbone-only torchvision dicts (``features.{idx}.*``) are accepted too,
since the published ``alex.pth``/``vgg.pth`` artifacts carry only the heads
and expect the torchvision backbone alongside.

No egress here, so conversion runs wherever a checkpoint already exists; the
mapping is validated numerically in `tests/models/test_lpips_parity.py` by
round-tripping a torch mirror's state dict and matching scores.
"""
from __future__ import annotations

import re
import sys
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

# torchvision `features` index -> metrics_tpu module name, per backbone
BACKBONE_INDEX_MAPS = {
    "alex": {0: "conv1", 3: "conv2", 6: "conv3", 8: "conv4", 10: "conv5"},
    "vgg": {
        0: "conv1_1", 2: "conv1_2",
        5: "conv2_1", 7: "conv2_2",
        10: "conv3_1", 12: "conv3_2", 14: "conv3_3",
        17: "conv4_1", 19: "conv4_2", 21: "conv4_3",
        24: "conv5_1", 26: "conv5_2", 28: "conv5_3",
    },
    "squeeze": {0: "conv1", 3: "fire2", 4: "fire3", 6: "fire4", 7: "fire5",
                9: "fire6", 10: "fire7", 11: "fire8", 12: "fire9"},
}

_BACKBONE_KEY = re.compile(r"^(?:net\.slice\d+|features)\.(\d+)\.(.+)$")
_HEAD_KEY = re.compile(r"^lin(\d+)\.(?:model\.)?1?\.?weight$")


def _conv_param(flax_prefix: str, rest: str, value: np.ndarray) -> Tuple[str, np.ndarray]:
    """Map a conv-layer parameter ('weight'/'bias', possibly nested under a
    Fire submodule like 'squeeze.weight') to its flax npz key + layout."""
    *submods, param = rest.split(".")
    path = "/".join([flax_prefix, *submods])
    if param == "weight":
        return f"{path}/kernel", value.transpose(2, 3, 1, 0)  # OIHW -> HWIO
    if param == "bias":
        return f"{path}/bias", value
    raise ValueError(f"Unrecognized conv parameter: {rest}")


def torch_key_to_npz(net_type: str, key: str, value: np.ndarray) -> Optional[Tuple[str, np.ndarray]]:
    """Map one lpips/torchvision state-dict entry to (npz_key, array); None drops it."""
    if key.startswith("scaling_layer."):
        return None  # shift/scale are compile-time constants in LPIPSNet
    if key.startswith("lins."):
        # lpips.LPIPS registers the heads twice (attributes lin{k} AND the
        # nn.ModuleList self.lins), so state_dict() duplicates every head
        # under lins.{k}.*; keep only the lin{k}.* copies
        return None
    match = _HEAD_KEY.match(key)
    if match:
        # (1, C, 1, 1) OIHW -> (1, 1, C, 1) HWIO
        return f"params/lin{match.group(1)}/kernel", value.transpose(2, 3, 1, 0)
    match = _BACKBONE_KEY.match(key)
    if match:
        index_map = BACKBONE_INDEX_MAPS[net_type]
        idx = int(match.group(1))
        if idx not in index_map:
            raise ValueError(f"features index {idx} is not a tapped conv for net_type={net_type!r}: {key}")
        return _conv_param(f"params/net/{index_map[idx]}", match.group(2), value)
    raise ValueError(f"Unrecognized lpips state-dict key: {key}")


def convert_state_dict(net_type: str, state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    if net_type not in BACKBONE_INDEX_MAPS:
        raise ValueError(f"net_type must be one of {tuple(BACKBONE_INDEX_MAPS)}, got {net_type!r}")
    out: Dict[str, np.ndarray] = {}
    for key, value in state.items():
        mapped = torch_key_to_npz(net_type, key, np.asarray(value))
        if mapped is not None:
            out[mapped[0]] = mapped[1]
    return out


def main(argv: Iterable[str]) -> None:
    net_type, src, dst = argv
    import torch

    state = torch.load(src, map_location="cpu")
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    converted = convert_state_dict(net_type, {k: v.numpy() for k, v in state.items()})
    np.savez(dst, **converted)
    print(f"wrote {len(converted)} arrays to {dst}")


if __name__ == "__main__":
    main(sys.argv[1:])
