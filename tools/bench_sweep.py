"""Per-metric update-throughput sweep across the device-path metric suite.

The BASELINE.md target "metric.update()/sec/chip over the 80-metric suite",
as a harness covering the FULL exported surface: device-path metrics run
their `as_functions` update jitted (donated state) or the eager module
update (cat states), host-side text metrics run the same update-only
protocol on the host (both sides are string processing), and wrappers run
around same-named bases — one JSON line each, plus a summary line whose
`not_swept` map enumerates everything a sweep row cannot measure and where
its cost IS measured (model-backed metrics, detection mAP:
`tools/bench_extended.py` and bench.py).

    python tools/bench_sweep.py            # current default backend
    JAX_PLATFORMS=cpu python tools/bench_sweep.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

BATCH, C = 4096, 16
STEPS, TRIALS = 20, 3


def _latency_ms(step, n, final=None):
    """Per-call latency percentiles (ms) over one extra ``n``-call pass,
    bucket-interpolated by the telemetry plane's ``LatencyHistogram`` — the
    same computation ``latency_stats()`` scrapes, so a sweep row's
    distribution column and a production percentile are comparable. Medians
    are far stabler run-to-run than the best-of mean throughput (the reason
    ``tools/sweep_regress.py``'s distribution-aware mode can gate tighter
    than the 5x mean-ratio threshold), and p99/p50 is the tail-ratio the
    gate watches for blowups."""
    from metrics_tpu.ops.telemetry import LatencyHistogram

    h = LatencyHistogram()
    for _ in range(n):
        t0 = time.perf_counter()
        step()
        h.observe(time.perf_counter() - t0)
    if final is not None:
        final()
    s = h.stats()
    return {
        "p50": round(s["p50_s"] * 1000.0, 4),
        "p95": round(s["p95_s"] * 1000.0, 4),
        "p99": round(s["p99_s"] * 1000.0, 4),
        "max": round(s["max_s"] * 1000.0, 4),
    }

# per-row timed-step overrides: the fused wrapper rows (both BootStrapper
# strategies and both MultioutputWrapper configs run as ONE program per step
# since round 5) get MORE steps so their one blocking clone-state sync per
# trial amortizes instead of dominating the short trial (at the default 20
# steps the ~110 ms sync reads as ~5x fewer updates/s than steady state)
EAGER_STEPS_OVERRIDE = {
    "BootStrapper(MeanSquaredError)": 100,
    "BootStrapper(MeanSquaredError,multinomial)": 100,
    "MultioutputWrapper(MeanSquaredError)": 100,
    "MultioutputWrapper(MeanSquaredError,no_nan_filter)": 100,
    "MinMaxMetric(Accuracy)": 100,
}


def _data(kind: str, rng):
    if kind == "probs":
        p = rng.rand(BATCH, C).astype(np.float32)
        return (p / p.sum(1, keepdims=True), rng.randint(0, C, BATCH))
    if kind == "binary":
        return (rng.rand(BATCH).astype(np.float32), rng.randint(0, 2, BATCH))
    if kind == "reg":
        p = rng.randn(BATCH).astype(np.float32)
        return (p, (p + 0.3 * rng.randn(BATCH)).astype(np.float32))
    if kind == "reg_pos":
        p = np.abs(rng.randn(BATCH)).astype(np.float32) + 0.1
        return (p, np.abs(p + 0.3 * rng.randn(BATCH)).astype(np.float32) + 0.1)
    if kind == "reg2d":
        p = rng.randn(BATCH, 8).astype(np.float32)
        return (p, (p + 0.3 * rng.randn(BATCH, 8)).astype(np.float32))
    if kind == "img":
        t = rng.rand(8, 3, 64, 64).astype(np.float32)
        return (np.clip(t + 0.05 * rng.randn(*t.shape), 0, 1).astype(np.float32), t)
    if kind == "audio":
        t = rng.randn(8, 4000).astype(np.float32)
        return ((t + 0.3 * rng.randn(*t.shape)).astype(np.float32), t)
    if kind == "mlabel_probs":
        return (rng.rand(BATCH, C).astype(np.float32), (rng.rand(BATCH, C) > 0.5).astype(np.int32))
    if kind == "mlabel_scores":
        return (rng.randn(BATCH, C).astype(np.float32), (rng.rand(BATCH, C) > 0.5).astype(np.int32))
    if kind == "retrieval":
        return (
            rng.rand(BATCH).astype(np.float32),
            (rng.rand(BATCH) > 0.7).astype(np.int32),
            np.repeat(np.arange(BATCH // 16), 16).astype(np.int64),
        )
    if kind == "probs2":
        p = rng.rand(BATCH, C).astype(np.float32)
        q = rng.rand(BATCH, C).astype(np.float32)
        return (p / p.sum(1, keepdims=True), q / q.sum(1, keepdims=True))
    if kind == "agg":
        return (rng.randn(BATCH).astype(np.float32),)
    if kind == "perplexity":
        return (
            rng.randn(BATCH // 16, 16, 32).astype(np.float32),
            rng.randint(0, 32, (BATCH // 16, 16)),
        )
    if kind == "pit":
        t = rng.randn(4, 2, 2000).astype(np.float32)
        return ((t + 0.3 * rng.randn(*t.shape)).astype(np.float32), t)
    if kind == "stoi":
        t = rng.randn(2, 8000).astype(np.float32)
        return ((t + 0.3 * rng.randn(*t.shape)).astype(np.float32), t)
    raise ValueError(kind)


# ----- host-side (text) sweep -------------------------------------------------
# The reference's text metrics are pure-python string processing (tokenize,
# n-gram counters, edit-distance DP) and so are ours (with opt-in native C++
# kernels for the DP hot loops) — both sides run on the host, so the
# update-only protocol compares like with like: no device is involved.

_VOCAB = (
    "the cat sat on a mat while the dog ran fast through tall green grass and "
    "a small bird sang over quiet hills near cold rivers during long warm days "
    "big old towns hold many open doors where young people walk late at night"
).split()


def _text_pairs(rng, n_pairs: int, wrap_targets: bool):
    """Synthetic hypothesis/reference sentence pairs (~20% word noise)."""
    preds, refs = [], []
    for _ in range(n_pairs):
        n = int(rng.randint(8, 24))
        ref = [_VOCAB[i] for i in rng.randint(0, len(_VOCAB), n)]
        pred = [
            _VOCAB[rng.randint(0, len(_VOCAB))] if rng.rand() < 0.2 else w
            for w in ref
        ]
        preds.append(" ".join(pred))
        refs.append(" ".join(ref))
    return (preds, [[r] for r in refs] if wrap_targets else refs)


def _squad_pairs(rng, n_pairs: int):
    preds, target = [], []
    for i in range(n_pairs):
        n = int(rng.randint(2, 6))
        ans = " ".join(_VOCAB[j] for j in rng.randint(0, len(_VOCAB), n))
        guess = ans if rng.rand() < 0.5 else " ".join(
            _VOCAB[j] for j in rng.randint(0, len(_VOCAB), n)
        )
        preds.append({"prediction_text": guess, "id": f"q{i}"})
        target.append({"answers": {"answer_start": [0], "text": [ans]}, "id": f"q{i}"})
    return (preds, target)


# (name, ctor, data builder, sentence pairs per update, steps per trial) —
# TER/EED get smaller corpora/steps: their per-pair DP (shift search, jump
# costs) is orders slower than the counter metrics on BOTH sides.
HOST_SWEEP = [
    ("BLEUScore", lambda mt: mt.BLEUScore(), lambda rng: _text_pairs(rng, 64, True), 64, 20),
    ("SacreBLEUScore", lambda mt: mt.SacreBLEUScore(), lambda rng: _text_pairs(rng, 64, True), 64, 20),
    ("CHRFScore", lambda mt: mt.CHRFScore(), lambda rng: _text_pairs(rng, 64, True), 64, 10),
    ("TranslationEditRate", lambda mt: mt.TranslationEditRate(), lambda rng: _text_pairs(rng, 16, True), 16, 5),
    ("ExtendedEditDistance", lambda mt: mt.ExtendedEditDistance(), lambda rng: _text_pairs(rng, 8, True), 8, 5),
    ("ROUGEScore", lambda mt: mt.ROUGEScore(), lambda rng: _text_pairs(rng, 64, False), 64, 10),
    ("WordErrorRate", lambda mt: mt.WordErrorRate(), lambda rng: _text_pairs(rng, 64, False), 64, 20),
    ("MatchErrorRate", lambda mt: mt.MatchErrorRate(), lambda rng: _text_pairs(rng, 64, False), 64, 20),
    ("WordInfoLost", lambda mt: mt.WordInfoLost(), lambda rng: _text_pairs(rng, 64, False), 64, 20),
    ("WordInfoPreserved", lambda mt: mt.WordInfoPreserved(), lambda rng: _text_pairs(rng, 64, False), 64, 20),
    ("CharErrorRate", lambda mt: mt.CharErrorRate(), lambda rng: _text_pairs(rng, 64, False), 64, 20),
    ("SQuAD", lambda mt: mt.SQuAD(), lambda rng: _squad_pairs(rng, 64), 64, 20),
]


SWEEP = [
    # (metric ctor lambda, data kind, samples per step)
    ("Accuracy", lambda mt: mt.Accuracy(num_classes=C, average="macro"), "probs", BATCH),
    ("Precision", lambda mt: mt.Precision(num_classes=C, average="macro"), "probs", BATCH),
    ("Recall", lambda mt: mt.Recall(num_classes=C, average="macro"), "probs", BATCH),
    ("F1Score", lambda mt: mt.F1Score(num_classes=C, average="macro"), "probs", BATCH),
    ("FBetaScore", lambda mt: mt.FBetaScore(num_classes=C, beta=2.0), "probs", BATCH),
    ("Specificity", lambda mt: mt.Specificity(num_classes=C), "probs", BATCH),
    ("Dice", lambda mt: mt.Dice(num_classes=C), "probs", BATCH),
    ("StatScores", lambda mt: mt.StatScores(num_classes=C, reduce="macro"), "probs", BATCH),
    ("ConfusionMatrix", lambda mt: mt.ConfusionMatrix(num_classes=C), "probs", BATCH),
    ("CohenKappa", lambda mt: mt.CohenKappa(num_classes=C), "probs", BATCH),
    ("MatthewsCorrCoef", lambda mt: mt.MatthewsCorrCoef(num_classes=C), "probs", BATCH),
    ("JaccardIndex", lambda mt: mt.JaccardIndex(num_classes=C), "probs", BATCH),
    ("CalibrationError", lambda mt: mt.CalibrationError(), "binary", BATCH),
    ("HammingDistance", lambda mt: mt.HammingDistance(), "mlabel_probs", BATCH),
    ("AUROC(exact,jit)", lambda mt: mt.AUROC(), "binary", BATCH),
    ("AveragePrecision(exact,jit)", lambda mt: mt.AveragePrecision(), "binary", BATCH),
    ("BinnedAveragePrecision", lambda mt: mt.BinnedAveragePrecision(num_classes=1, thresholds=100), "binary", BATCH),
    ("KLDivergence", lambda mt: mt.KLDivergence(), "probs2", BATCH),
    ("MeanSquaredError", lambda mt: mt.MeanSquaredError(), "reg", BATCH),
    ("MeanAbsoluteError", lambda mt: mt.MeanAbsoluteError(), "reg", BATCH),
    ("MeanAbsolutePercentageError", lambda mt: mt.MeanAbsolutePercentageError(), "reg_pos", BATCH),
    ("MeanSquaredLogError", lambda mt: mt.MeanSquaredLogError(), "reg_pos", BATCH),
    ("ExplainedVariance", lambda mt: mt.ExplainedVariance(), "reg", BATCH),
    ("R2Score", lambda mt: mt.R2Score(), "reg", BATCH),
    ("PearsonCorrCoef", lambda mt: mt.PearsonCorrCoef(), "reg", BATCH),
    ("SpearmanCorrCoef", lambda mt: mt.SpearmanCorrCoef(), "reg", BATCH),
    ("CosineSimilarity", lambda mt: mt.CosineSimilarity(), "reg2d", BATCH),
    ("TweedieDevianceScore", lambda mt: mt.TweedieDevianceScore(power=1.5), "reg_pos", BATCH),
    ("MeanMetric", lambda mt: mt.MeanMetric(), "agg", BATCH),
    ("SumMetric", lambda mt: mt.SumMetric(), "agg", BATCH),
    ("MaxMetric", lambda mt: mt.MaxMetric(), "agg", BATCH),
    ("PeakSignalNoiseRatio", lambda mt: mt.PeakSignalNoiseRatio(data_range=1.0), "img", 8),
    ("StructuralSimilarityIndexMeasure", lambda mt: mt.StructuralSimilarityIndexMeasure(), "img", 8),
    ("MultiScaleSSIM", lambda mt: mt.MultiScaleStructuralSimilarityIndexMeasure(), "img", 8),
    ("UniversalImageQualityIndex", lambda mt: mt.UniversalImageQualityIndex(), "img", 8),
    ("SpectralAngleMapper", lambda mt: mt.SpectralAngleMapper(), "img", 8),
    ("SignalNoiseRatio", lambda mt: mt.SignalNoiseRatio(), "audio", 8),
    ("ScaleInvariantSignalDistortionRatio", lambda mt: mt.ScaleInvariantSignalDistortionRatio(), "audio", 8),
    ("SignalDistortionRatio", lambda mt: mt.SignalDistortionRatio(), "audio", 8),
    ("ScaleInvariantSignalNoiseRatio", lambda mt: mt.ScaleInvariantSignalNoiseRatio(), "audio", 8),
    ("HingeLoss", lambda mt: mt.HingeLoss(), "binary", BATCH),
    ("CoverageError", lambda mt: mt.CoverageError(), "mlabel_scores", BATCH),
    ("LabelRankingAveragePrecision", lambda mt: mt.LabelRankingAveragePrecision(), "mlabel_scores", BATCH),
    ("LabelRankingLoss", lambda mt: mt.LabelRankingLoss(), "mlabel_scores", BATCH),
    ("MinMetric", lambda mt: mt.MinMetric(), "agg", BATCH),
    ("BinnedPrecisionRecallCurve", lambda mt: mt.BinnedPrecisionRecallCurve(num_classes=1, thresholds=100), "binary", BATCH),
    ("BinnedRecallAtFixedPrecision", lambda mt: mt.BinnedRecallAtFixedPrecision(num_classes=1, min_precision=0.5, thresholds=100), "binary", BATCH),
    ("ROC(exact,jit)", lambda mt: mt.ROC(), "binary", BATCH),
    ("PrecisionRecallCurve(exact,jit)", lambda mt: mt.PrecisionRecallCurve(), "binary", BATCH),
    ("ErrorRelativeGlobalDimensionlessSynthesis", lambda mt: mt.ErrorRelativeGlobalDimensionlessSynthesis(), "img", 8),
    ("SpectralDistortionIndex", lambda mt: mt.SpectralDistortionIndex(), "img", 8),
    ("RetrievalMAP", lambda mt: mt.RetrievalMAP(), "retrieval", BATCH),
    ("RetrievalMRR", lambda mt: mt.RetrievalMRR(), "retrieval", BATCH),
    ("RetrievalNormalizedDCG", lambda mt: mt.RetrievalNormalizedDCG(), "retrieval", BATCH),
    ("RetrievalPrecision", lambda mt: mt.RetrievalPrecision(k=4), "retrieval", BATCH),
    ("RetrievalRecall", lambda mt: mt.RetrievalRecall(k=4), "retrieval", BATCH),
    ("RetrievalHitRate", lambda mt: mt.RetrievalHitRate(k=4), "retrieval", BATCH),
    ("RetrievalFallOut", lambda mt: mt.RetrievalFallOut(k=4), "retrieval", BATCH),
    ("RetrievalRPrecision", lambda mt: mt.RetrievalRPrecision(), "retrieval", BATCH),
    ("CatMetric", lambda mt: mt.CatMetric(), "agg", BATCH),
    ("WeightedMeanAbsolutePercentageError", lambda mt: mt.WeightedMeanAbsolutePercentageError(), "reg_pos", BATCH),
    ("SymmetricMeanAbsolutePercentageError", lambda mt: mt.SymmetricMeanAbsolutePercentageError(), "reg_pos", BATCH),
    ("Perplexity", lambda mt: mt.Perplexity(), "perplexity", BATCH),
    # each side binds ITS OWN functional (the lambda's module arg), so the
    # reference arm wraps the torch si-snr, not ours
    ("PermutationInvariantTraining", lambda mt: mt.PermutationInvariantTraining(
        mt.functional.scale_invariant_signal_noise_ratio, "max"), "pit", 4),
    ("ShortTimeObjectiveIntelligibility(native)", lambda mt: mt.ShortTimeObjectiveIntelligibility(10000), "stoi", 2),
    ("AUC", lambda mt: mt.AUC(reorder=True), "reg", BATCH),
    ("RetrievalPrecisionRecallCurve", lambda mt: mt.RetrievalPrecisionRecallCurve(max_k=10), "retrieval", BATCH),
    ("RetrievalRecallAtFixedPrecision", lambda mt: mt.RetrievalRecallAtFixedPrecision(min_precision=0.5, max_k=10), "retrieval", BATCH),
    # wrappers: the wrapped kernel's cost plus the wrapper's bookkeeping —
    # both sides wrap their own same-named base metric
    ("MinMaxMetric(Accuracy)", lambda mt: mt.MinMaxMetric(mt.Accuracy(num_classes=C, average="macro")), "probs", BATCH),
    ("ClasswiseWrapper(Accuracy)", lambda mt: mt.ClasswiseWrapper(mt.Accuracy(num_classes=C, average=None)), "probs", BATCH),
    ("BootStrapper(MeanSquaredError)", lambda mt: mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4), "reg", BATCH),
    ("BootStrapper(MeanSquaredError,multinomial)", lambda mt: mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4, sampling_strategy="multinomial"), "reg", BATCH),
    ("MultioutputWrapper(MeanSquaredError)", lambda mt: mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=8), "reg2d", BATCH),
    ("MultioutputWrapper(MeanSquaredError,no_nan_filter)", lambda mt: mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=8, remove_nans=False), "reg2d", BATCH),
]

# deferred_per_step rows: the UNMODIFIED eager module API (`metric.update`
# per step) with deferred micro-batched dispatch on — calls enqueue and
# flush as stacked donated-state lax.scan programs at the queue threshold,
# so the eager loop amortizes the per-program backend round trip without a
# forward_many rewrite (ISSUE 3). Same shaped floor probes as the eager
# rows; the trailing metric_state read is the observation that forces the
# final flush, so every flush the loop incurs is inside the timed region.
DEFERRED_SWEEP = [
    ("Accuracy(deferred_per_step)", lambda mt: mt.Accuracy(num_classes=C, average="macro"), "probs", BATCH),
    ("MeanSquaredError(deferred_per_step)", lambda mt: mt.MeanSquaredError(), "reg", BATCH),
    ("MeanMetric(deferred_per_step)", lambda mt: mt.MeanMetric(), "agg", BATCH),
]
DEFERRED_STEPS = 200  # >= the default queue threshold so flushes amortize


# Explanations attached to outlier rows so no ratio is "unexplained".
# FAST (>10x) jit rows share one structural cause, recorded in the summary:
# a fused donated-state XLA program on the TPU runs in the backend's
# pipelined regime while torch-CPU executes tens of eager ops per update —
# the same 17-70x the headline bench measures. Slow (<0.1x) rows and fast
# rows with a DIFFERENT cause than the blanket one are noted here.
OUTLIER_NOTES = {
    "BinnedPrecisionRecallCurve": "beyond the blanket jit-vs-eager gap: torch-CPU loops the threshold axis per update; ours is one (T,B) broadcast kernel",
    "BinnedAveragePrecision": "same thresholds-loop asymmetry as BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision": "same thresholds-loop asymmetry as BinnedPrecisionRecallCurve",
    "SignalDistortionRatio": "torch-CPU runs a per-update Toeplitz solve; ours is a batched device solve inside the jit program",
    "LabelRankingAveragePrecision": "the reference's update loops samples in python (reference functional/classification/ranking.py); ours is one vectorized segment program",
    "LabelRankingLoss": "same per-sample python loop asymmetry as LabelRankingAveragePrecision",
    "CoverageError": "same per-sample python loop asymmetry as LabelRankingAveragePrecision",
    "AUROC(exact,jit)": "both sides now defer curve work to compute: the reference appends tensors, ours appends RAW rows after metadata-only mode validation — the update-only timing is symmetric",
    "AveragePrecision(exact,jit)": "same raw-append symmetry as AUROC",
    "ROC(exact,jit)": "same raw-append symmetry as AUROC",
    "PrecisionRecallCurve(exact,jit)": "same raw-append symmetry as AUROC",
    "SpearmanCorrCoef": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalNormalizedDCG": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalMAP": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalMRR": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalPrecision": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalRecall": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalHitRate": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalFallOut": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalRPrecision": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "CatMetric": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "CosineSimilarity": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "UniversalImageQualityIndex": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "SpectralAngleMapper": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "ErrorRelativeGlobalDimensionlessSynthesis": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "SpectralDistortionIndex": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "StructuralSimilarityIndexMeasure": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "MultiScaleSSIM": "buffers raw images (cat state) both sides; ours appends the raw batch with zero dispatches (deferred canonicalization), so the row sits at python-append cost",
    "PeakSignalNoiseRatio": "scalar-state image metric; ratio reflects tunnel dispatch overhead when below 1x",
    "Perplexity": "beyond the blanket jit-vs-eager gap: the reference materializes per-token probability gathers eagerly per update; ours is one fused logsumexp-gather program",
    "AUC": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalPrecisionRecallCurve": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "RetrievalRecallAtFixedPrecision": "append-only update both sides; ours buffers RAW rows (zero-dispatch list append, deferred canonicalization — docs/performance.md); residual ratio is python bookkeeping vs torch's in-process append",
    "MinMaxMetric(Accuracy)": "wrapper state lives in the child metric; the child update runs as the fused single-program update (and forward as the fused minmax program, round 5 — docs/performance.md), so the row sits at the tunnel's per-program floor — below torch-CPU's in-process step, see the row's own floor_bound_factor",
    "ClasswiseWrapper(Accuracy)": "the wrapper's own as_functions composes the child kernels (labeling happens at compute), so the update is the child's fused jit program; the reference fans out eagerly",
    "BootStrapper(MeanSquaredError)": "poisson bootstrap runs as ONE donated-state weighted-row program per step (counts as row weights over vmapped per-row state deltas, certified vs the eager path; draws prefetched; program shared across same-config instances by the dispatch engine — wrappers/bootstrapping.py, ops/engine.py). Its floor probe is GENUINELY shaped since round 6: same stacked clone states, same (B, leaf) row-delta buffers, one-op stand-in kernel — the row's floor_bound_factor is apples-to-apples",
    "BootStrapper(MeanSquaredError,multinomial)": "all clones run as ONE donated-state vmapped program per update (wrappers/_fanout.py fused fan-out via ops/engine.py); the floor probe carries the same stacked states + (C,B) index matrix + gather shapes, so the residual factor over it is the backend's per-program cost, not metric code",
    "MultioutputWrapper(MeanSquaredError)": "remove_nans=True zero-weights NaN rows INSIDE the one-program column fan-out since round 5 (no host mask read — wrappers/multioutput.py); residual gap vs torch-CPU is the tunnel's per-program cost, see the row's floor_bound_factor",
    "MultioutputWrapper(MeanSquaredError,no_nan_filter)": "remove_nans=False has static shapes: all column clones run as ONE vmapped program per update (wrappers/multioutput.py fused fan-out)",
    "Accuracy(deferred_per_step)": "eager module API with the deferral queue on: ~1 stacked scan dispatch per METRICS_TPU_DEFER_MAX steps instead of 1 per step — large ratios are the queue amortizing the backend round trip the plain eager rows pay per call",
    "MeanSquaredError(deferred_per_step)": "same deferral amortization as Accuracy(deferred_per_step)",
    "MeanMetric(deferred_per_step)": "same deferral amortization as Accuracy(deferred_per_step)",
    # host-side text rows: both sides are host string processing; large
    # ratios come from the native C++ DP kernels (metrics_tpu/native/)
    "WordErrorRate": "native C++ Levenshtein kernel (metrics_tpu/native) vs the reference's python DP",
    "MatchErrorRate": "native C++ Levenshtein kernel vs the reference's python DP",
    "WordInfoLost": "native C++ Levenshtein kernel vs the reference's python DP",
    "WordInfoPreserved": "native C++ Levenshtein kernel vs the reference's python DP",
    "CharErrorRate": "native C++ Levenshtein kernel vs the reference's python DP",
    "ROUGEScore": "native C++ LCS kernel for rougeL/rougeLsum vs the reference's python DP",
    "TranslationEditRate": "native C++ Levenshtein inner loop inside the shift search vs the reference's python implementation",
    "ExtendedEditDistance": "native C++ EED DP kernel vs the reference's python implementation",
    "CHRFScore": "the reference constructs a fresh torch tensor per n-gram order per sentence (reference chrf.py:181,208 — its own UserWarning flags it); ours keeps counters as host floats until one batched conversion",
    "BLEUScore": "n-gram counters both sides; python dict work dominates",
    "SacreBLEUScore": "tokenize + n-gram counters both sides; python regex/dict work dominates",
    "SQuAD": "normalized string match both sides; python string work dominates",
}

FAST_BLANKET_NOTE = (
    "rows >10x with no individual note share one structural cause: a fused "
    "donated-state XLA program on the TPU (pipelined regime) vs tens of "
    "eager torch-CPU ops per update — the same gap the headline "
    "fused_suite_update_throughput workload measures"
)


def _time_reference(name: str, ctor, data, steps: int = STEPS) -> float:
    """Per-update throughput of the mounted reference (torch-CPU), same
    update-only protocol as our side. Returns 0.0 when unavailable.

    Host-side (text) rows pass their string/dict corpora through untouched —
    only numeric arrays are converted to torch tensors."""
    try:
        from tests.helpers.reference_oracle import get_reference

        tm = get_reference()
        if tm is None:
            return 0.0
        import torch

        tdata = tuple(
            d if isinstance(d, (list, tuple, dict, str)) else torch.from_numpy(np.asarray(d))
            for d in data
        )
        metric = ctor(tm)
        metric.update(*tdata)  # warmup
        best = float("inf")
        for _ in range(TRIALS):
            metric.reset()
            start = time.perf_counter()
            for _ in range(steps):
                metric.update(*tdata)
            best = min(best, time.perf_counter() - start)
        return steps / best
    except Exception:
        return 0.0


def main() -> None:
    import os

    json_out = None
    if "--json" in sys.argv:
        flag_pos = sys.argv.index("--json")
        if flag_pos + 1 >= len(sys.argv):
            raise SystemExit("usage: bench_sweep.py [--json OUT.json]")
        json_out = sys.argv[flag_pos + 1]

    # throughput harness: value-check the first batch per signature only
    # (see docs/performance.md "Input validation cost on remote backends")
    os.environ.setdefault("METRICS_TPU_VALIDATION", "first")
    import jax

    import metrics_tpu as mt

    rng = np.random.RandomState(0)
    results = []
    # jit-mode metrics (no list states) run FIRST: they never read device
    # values, so they measure in the backend's fully-pipelined regime. The
    # first eager module-API update performs a D2H value check, after which
    # the tunneled backend charges a full blocking-sync round trip per
    # synchronization for the rest of the session (see
    # docs/performance.md "The device-to-host sync cliff") — so all eager
    # rows share one post-D2H regime instead of poisoning jit rows.
    def _is_jit_mode(entry):
        """jit rows: array-only states AND a traceable update.

        Traceability is probed with ``jax.eval_shape`` (abstract tracing —
        no compile, no dispatch, no device->host read), so the probe cannot
        flip the backend out of its pipelined regime the way executing an
        eager fallback mid-jit-block would. Host-DSP metrics (e.g. native
        STOI's silence segmentation) fail the trace and take the eager
        protocol."""
        name, ctor, kind, samples = entry
        try:
            init, upd, _ = ctor(mt).as_functions()
            state = init()
            # child-holding wrappers now RAISE from as_functions (caught by
            # the enclosing except -> eager); this guard stays as defense in
            # depth should a future metric export an empty state dict, whose
            # jitted update XLA would dead-code-eliminate into a no-op
            if not state:
                return False
            if any(isinstance(v, list) for v in state.values()):
                return False
            kdata = _data(kind, np.random.RandomState(0))
            abstract = tuple(jax.ShapeDtypeStruct(np.shape(d), np.asarray(d).dtype) for d in kdata)
            jax.eval_shape(upd, state, *abstract)
            return True
        except Exception:
            return False

    modes = [_is_jit_mode(e) for e in SWEEP]
    modes_by_name = {e[0]: m for e, m in zip(SWEEP, modes)}
    ordered = [e for e, m in zip(SWEEP, modes) if m] + [e for e, m in zip(SWEEP, modes) if not m]
    np_data_by_name = {}  # host copies kept for the post-pass reference arm

    def _shaped_floor_ms(metric, steps: int) -> float:
        """Per-PROGRAM cost of a chained jitted step with this metric's exact
        state-buffer profile (bench.py's shaped-probe methodology, per row).

        Runs immediately after the row's own timing, so it sees the SAME
        backend regime (pipelined for jit rows, post-D2H for eager rows), and
        its trailing blocking sync amortizes over the row's OWN step count —
        `floor_bound_factor` is then an apples-to-apples bound. Returns 0.0
        for list-state metrics (their per-update cost is a host append, not a
        program; a program floor is the wrong model there).
        """

        def collect(m, prefix, into):
            for k, v in m.metric_state.items():
                into[prefix + k] = v
            for cname, child in m._named_child_metrics():
                collect(child, f"{prefix}{cname}.", into)

        try:
            state: dict = {}
            collect(metric, "", state)
            if not state or any(isinstance(v, list) for v in state.values()):
                return 0.0
            # donated like the real dispatch-engine programs: the floor must
            # model the same in-place aliasing the fused paths now compile
            g = jax.jit(lambda st: {k: a + 1 for k, a in st.items()}, donate_argnums=(0,))
            box = g({k: jax.numpy.asarray(v).copy() for k, v in state.items()})
            jax.block_until_ready(box)
            best = float("inf")
            for _ in range(2):
                start = time.perf_counter()
                for _ in range(steps):
                    box = g(box)
                jax.block_until_ready(box)
                best = min(best, (time.perf_counter() - start) / steps)
            return best
        except Exception:
            return 0.0

    def _fanout_floor_ms(metric, data, steps: int) -> float:
        """GENUINELY-SHAPED floor for the one-program bootstrap rows
        (VERDICT r5 Next #1: the add-one probe was "substantially smaller"
        than the real program, making floor_bound_factor apples-to-oranges).

        The probe program carries the real paths' full buffer profile —
        stacked per-clone states, the (num_bootstraps, B) draw/weight
        matrix, the data operands, and (poisson) the (B, leaf) per-row
        delta intermediates of the vmapped-update + weight-contraction
        pipeline — with a one-op stand-in update kernel, donated state,
        chained steps, trailing sync amortized over the row's own count.
        """
        import jax.numpy as jnp

        from metrics_tpu.wrappers._fanout import weighted_state_apply

        clones = getattr(metric, "metrics", None)
        if not clones:
            return 0.0
        try:
            if any(isinstance(v, list) for m in clones for v in m.metric_state.values()):
                return 0.0
            # donation-safe copies: the probe must never consume the live
            # clone state buffers
            states = [
                {k: jnp.asarray(v).copy() for k, v in m.metric_state.items()} for m in clones
            ]
            arrs = tuple(jnp.asarray(d) for d in data)
            batch = int(arrs[0].shape[0])
            n_clones = len(clones)
            prng = np.random.RandomState(0)

            def upd_like(state, *rows):
                bump = sum(r.astype(jnp.float32).sum() for r in rows)
                return {k: v + bump.astype(v.dtype) for k, v in state.items()}

            if getattr(metric, "sampling_strategy", None) == "multinomial":
                mat = jnp.asarray(prng.randint(0, batch, (n_clones, batch)))

                def program(states, idx, *a):
                    def one(state, rows):
                        ra = [jnp.take(x, rows, axis=0) for x in a]
                        return upd_like(state, *ra)

                    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
                    out = jax.vmap(one)(stacked, idx)
                    return [jax.tree.map(lambda x: x[i], out) for i in range(len(states))]

            else:  # poisson: counts-as-row-weights over vmapped per-row deltas
                mat = jnp.asarray(prng.poisson(1, (n_clones, batch)).astype(np.int32))

                def program(states, w, *a):
                    init = {k: jnp.zeros_like(v) for k, v in states[0].items()}

                    def one_row(row):
                        ra = jax.tree.map(lambda x: x[None], row)
                        return upd_like(init, *ra)

                    deltas = jax.vmap(one_row)(tuple(a))
                    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
                    new = weighted_state_apply(stacked, deltas, w)
                    return [jax.tree.map(lambda x: x[i], new) for i in range(len(states))]

            prog = jax.jit(program, donate_argnums=(0,))
            box = {"st": [dict(s) for s in states]}

            def step():
                box["st"] = prog(box["st"], mat, *arrs)
                return box["st"]

            step()
            jax.block_until_ready(box["st"])
            best = float("inf")
            for _ in range(2):
                start = time.perf_counter()
                for _ in range(steps):
                    step()
                jax.block_until_ready(box["st"])
                best = min(best, (time.perf_counter() - start) / steps)
            return best
        except Exception:
            return 0.0
    for name, ctor, kind, samples in ordered:
        try:
            data = _data(kind, rng)
            np_data_by_name[name] = data
            # the BASELINE target is metric.update()/sec/chip — the cost of the
            # update program itself. Inputs are placed on device up front (in a
            # training loop they already live there, produced by the previous
            # step); passing numpy per call would time the host->device
            # transfer through the (variable-latency) backend tunnel instead,
            # which is what made early sweep recordings report 100x outliers.
            data = tuple(jax.device_put(jax.numpy.asarray(d)) for d in data)
            jax.block_until_ready(data)
            metric = ctor(mt)
            eager_mode = not modes_by_name[name]
            steps = STEPS
            if eager_mode:
                # cat-state metrics (growing pytree would retrace per step)
                # AND trace-failing host-DSP metrics (e.g. native STOI) run
                # the eager module update — their supported hot path
                mode = "eager"
                # per-row step override (see EAGER_STEPS_OVERRIDE): fewer
                # steps for sync/recompile-floor rows (at 20 steps x 3 trials
                # the poisson BootStrapper row alone costs ~5 wall-clock
                # minutes), more for the fused fan-out row whose per-trial
                # sync must amortize
                steps = EAGER_STEPS_OVERRIDE.get(name, STEPS)
                jdata = list(data)

                def _sync_all(m=metric):
                    # child-holding wrappers have an empty own metric_state;
                    # the trial must wait out the CHILDREN's queued work too
                    jax.block_until_ready(
                        [m.metric_state] + [c.metric_state for _, c in m._named_child_metrics()]
                    )

                metric.update(*jdata)  # warmup (device transfer + compile)
                _sync_all()
                best = float("inf")
                for _ in range(TRIALS):
                    metric.reset()
                    start = time.perf_counter()
                    for _ in range(steps):
                        metric.update(*jdata)
                    _sync_all()
                    best = min(best, time.perf_counter() - start)
                metric.reset()
                latency = _latency_ms(lambda: metric.update(*jdata), steps, _sync_all)
            else:
                mode = "jit"
                init, upd, _ = metric.as_functions()
                state0 = init()
                fused = jax.jit(upd, donate_argnums=(0,))
                # two warmup calls: the first compiles for the default state,
                # the second catches any residual state-avals drift (a dtype
                # the update widens, a weak type a custom default kept) so the
                # timed region never contains a recompile
                state = fused(state0, *data)
                state = fused(state, *data)
                jax.block_until_ready(state)
                best = float("inf")
                for _ in range(TRIALS):
                    start = time.perf_counter()
                    for _ in range(STEPS):
                        state = fused(state, *data)
                    jax.block_until_ready(state)
                    best = min(best, time.perf_counter() - start)
                sbox = {"st": state}

                def _fused_step(f=fused, d=data):
                    sbox["st"] = f(sbox["st"], *d)

                latency = _latency_ms(
                    _fused_step, STEPS, lambda: jax.block_until_ready(sbox["st"])
                )
            rate = steps * samples / best
            row = {
                "metric": name,
                "mode": mode,
                "updates_per_s": round(steps / best, 1),
                "samples_per_s": round(rate, 1),
                "latency_ms": latency,
            }
            if isinstance(metric, mt.BootStrapper):
                # the one-program bootstrap rows get the GENUINELY-shaped
                # probe (same state leaves, same row-delta output buffers as
                # the real weighted-row/vmapped program — VERDICT r5 Next #1)
                floor_s = _fanout_floor_ms(metric, data, steps)
                if floor_s > 0:
                    row["floor_probe"] = "fanout-shaped (weighted-row/vmap buffer profile)"
            else:
                floor_s = _shaped_floor_ms(metric, steps)
            if floor_s > 0:
                row["floor_ms_per_program"] = round(floor_s * 1000.0, 3)
                row["floor_bound_factor"] = round((best / steps) / floor_s, 2)
            results.append(row)
            print(json.dumps(results[-1]))
        except Exception as err:
            print(json.dumps({"metric": name, "error": str(err)[:160]}))

    # deferred_per_step rows: eager module-API update loop with the deferral
    # queue on (the post-D2H regime is already active, which is exactly the
    # regime the queue exists to amortize)
    from metrics_tpu.ops import engine as _defer_engine

    steps_by_name = {}
    for name, ctor, kind, samples in DEFERRED_SWEEP:
        try:
            data = _data(kind, np.random.RandomState(0))
            np_data_by_name[name] = data
            steps_by_name[name] = DEFERRED_STEPS
            jdata = tuple(jax.device_put(jax.numpy.asarray(d)) for d in data)
            jax.block_until_ready(jdata)
            _defer_engine.set_deferred_dispatch(True)
            metric = ctor(mt)
            # warmup mirrors the timed protocol exactly: the eager-validated
            # first call, then a full timed-loop's worth of enqueues so every
            # power-of-two flush bucket the steady state hits is compiled
            metric.update(*jdata)
            for _ in range(DEFERRED_STEPS):
                metric.update(*jdata)
            jax.block_until_ready(metric.metric_state)  # observation: flush
            from metrics_tpu.ops import perf as _perf
            from metrics_tpu.ops import telemetry as _phase_telemetry

            lat0 = _phase_telemetry.latency_stats()
            best = float("inf")
            for _ in range(TRIALS):
                metric.reset()
                start = time.perf_counter()
                for _ in range(DEFERRED_STEPS):
                    metric.update(*jdata)
                jax.block_until_ready(metric.metric_state)
                best = min(best, time.perf_counter() - start)
            # archived phase columns (ISSUE 12): per-phase milliseconds the
            # timed trials spent, recorded from the telemetry latency plane —
            # what tools/sweep_regress.py --explain attributes a future
            # regression to (flush stall vs compile-in-loop vs dispatch)
            phases_ms = _perf.phase_columns(lat0, _phase_telemetry.latency_stats())
            metric.reset()
            latency = _latency_ms(
                lambda: metric.update(*jdata),
                DEFERRED_STEPS,
                lambda: jax.block_until_ready(metric.metric_state),
            )
            row = {
                "metric": name,
                "mode": "deferred",
                "updates_per_s": round(DEFERRED_STEPS / best, 1),
                "samples_per_s": round(DEFERRED_STEPS * samples / best, 1),
                "latency_ms": latency,
                "phases_ms": phases_ms,
            }
            floor_s = _shaped_floor_ms(metric, DEFERRED_STEPS)
            if floor_s > 0:
                row["floor_ms_per_program"] = round(floor_s * 1000.0, 3)
                # < 1.0 expected: the deferred loop dispatches ~1 program per
                # queue window, so its per-STEP cost sits BELOW the per-
                # program floor that bounds the eager rows
                row["floor_bound_factor"] = round((best / DEFERRED_STEPS) / floor_s, 2)
            results.append(row)
            print(json.dumps(row))
        except Exception as err:
            print(json.dumps({"metric": name, "error": str(err)[:160]}))

    # host-side text rows: pure host string processing on both sides; they
    # run after the device rows (their update still accumulates counters as
    # tiny jnp scalars, which flips nothing — the eager D2H regime is already
    # active by this point)
    for name, ctor, data_builder, samples, steps in HOST_SWEEP:
        try:
            data = data_builder(np.random.RandomState(0))
            np_data_by_name[name] = data
            steps_by_name[name] = steps
            metric = ctor(mt)
            metric.update(*data)  # warmup (incl. native-kernel first build)
            best = float("inf")
            for _ in range(TRIALS):
                metric.reset()
                start = time.perf_counter()
                for _ in range(steps):
                    metric.update(*data)
                best = min(best, time.perf_counter() - start)
            metric.reset()
            latency = _latency_ms(lambda: metric.update(*data), steps)
            row = {
                "metric": name,
                "mode": "host",
                "updates_per_s": round(steps / best, 1),
                "samples_per_s": round(steps * samples / best, 1),
                "latency_ms": latency,
            }
            results.append(row)
            print(json.dumps(row))
        except Exception as err:
            print(json.dumps({"metric": name, "error": str(err)[:160]}))

    # sync_per_call rows (ISSUE 5): whole-suite sync round trips, coalesced
    # (one packed payload collective slot + one donated unpack program) vs
    # the per-state protocol (2 collective slots per state per metric).
    # collectives_per_sync is the multi-process cost model — each slot is a
    # blocking ~sync_roundtrip_ms exchange on the tunneled backend; no
    # reference arm (the torch reference needs a live process group).
    for label, coalesce in (("suite_sync(coalesced)", True), ("suite_sync(per_state)", False)):
        try:
            os.environ["METRICS_TPU_SYNC_COALESCE"] = "1" if coalesce else "0"
            from metrics_tpu.ops import engine as _sync_engine

            dist_on = lambda: True  # noqa: E731
            coll = mt.MetricCollection(
                {
                    "mean": mt.MeanMetric(),
                    "mse": mt.MeanSquaredError(),
                    "mae": mt.MeanAbsoluteError(),
                    "acc": mt.Accuracy(),
                }
            )
            reg = _data("binary", np.random.RandomState(0))
            coll.update(jax.numpy.asarray(reg[0]), jax.numpy.asarray(reg[1]))
            coll.sync(distributed_available=dist_on)  # warmup: programs compile
            coll.unsync()
            n_syncs = max(3, STEPS // 5)
            from metrics_tpu.ops import perf as _sync_perf
            from metrics_tpu.ops import telemetry as _sync_telemetry

            s0 = _sync_engine.engine_stats()
            lat0 = _sync_telemetry.latency_stats()
            best = float("inf")
            for _ in range(TRIALS):
                start = time.perf_counter()
                for _ in range(n_syncs):
                    coll.sync(distributed_available=dist_on)
                    coll.unsync()
                jax.block_until_ready(coll["mean"].value)
                best = min(best, time.perf_counter() - start)
            s1 = _sync_engine.engine_stats()
            per_sync = (
                s1["sync_shape_collectives"] + s1["sync_payload_collectives"]
                - s0["sync_shape_collectives"] - s0["sync_payload_collectives"]
            ) / (n_syncs * TRIALS)
            # archived sync phase columns: pack/serialize/wire/unpack/
            # orchestrate milliseconds over the timed cycles, what --explain
            # names when a sync row's gate fails round over round
            phases_ms = _sync_perf.phase_columns(lat0, _sync_telemetry.latency_stats())
            def _cycle():
                coll.sync(distributed_available=dist_on)
                coll.unsync()

            latency = _latency_ms(
                _cycle, n_syncs, lambda: jax.block_until_ready(coll["mean"].value)
            )
            row = {
                "metric": label,
                "mode": "sync",
                "updates_per_s": round(n_syncs / best, 1),
                "collectives_per_sync": round(per_sync, 2),
                "latency_ms": latency,
                "phases_ms": phases_ms,
            }
            results.append(row)
            print(json.dumps(row))
        except Exception as err:
            print(json.dumps({"metric": label, "error": str(err)[:160]}))
        finally:
            os.environ.pop("METRICS_TPU_SYNC_COALESCE", None)

    # async_sync_overlap row (ISSUE 13): the wire off the critical path —
    # wire_hidden_fraction is what sweep_regress gates round over round (a
    # healthy fraction collapsing below 0.5 means the overlap broke); the
    # full overlap methodology (simulated slow transport, sized window)
    # lives in bench.py bench_async_sync_overlap, reused here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_async_sync_overlap()
        row = {
            "metric": "async_sync(overlap)",
            "mode": "sync",
            "updates_per_s": round(probe["async_steps_per_s"], 1),
            "blocking_updates_per_s": round(probe["blocking_steps_per_s"], 1),
            "overlap_speedup": round(probe["overlap_speedup"], 3),
            "wire_hidden_fraction": round(probe["wire_hidden_fraction"], 4),
            "simulated_rtt_ms": probe["simulated_rtt_ms"],
            "updates_per_cycle": probe["updates_per_cycle"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "async_sync(overlap)", "error": str(err)[:160]}))

    # sync_quant_payload row (ISSUE 13): bytes on the wire per suite sync
    # under the quantized lanes (bf16/int8 vs f32), archived so a round can
    # prove the payload shrank (and by how much) without rerunning bench.py.
    try:
        import bench as _bench

        probe = _bench.bench_sync_quant_payload()
        row = {
            "metric": "suite_sync(quant_payload)",
            "mode": "sync",
            "f32_bytes_per_sync": probe["f32_bytes_per_sync"],
            "bf16_bytes_per_sync": probe["bf16_bytes_per_sync"],
            "int8_bytes_per_sync": probe["int8_bytes_per_sync"],
            "bf16_reduction": round(probe["bf16_reduction"], 3),
            "int8_reduction": round(probe["int8_reduction"], 3),
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "suite_sync(quant_payload)", "error": str(err)[:160]}))

    # ingraph_step row (ISSUE 16): the functional-core whole-suite step —
    # host_collectives_per_step and wire_share are what sweep_regress gates
    # round over round (both must stay EXACTLY 0: an in-graph step that
    # starts issuing host collectives, or growing a wire phase, means the
    # zero-host-round-trip contract broke); the full step methodology
    # (donated jitted FuncState program, counted host sync counters) lives
    # in bench.py bench_ingraph_step, reused here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_ingraph_step()
        row = {
            "metric": "ingraph_step(functional_core)",
            "mode": "sync",
            "updates_per_s": round(probe["steps_per_s"], 1),
            "ms_per_step": round(probe["ms_per_step"], 4),
            "host_collectives_per_step": round(probe["host_collectives_per_step"], 4),
            "wire_share": round(probe["wire_share"], 4),
            "latency_ms": probe["latency_ms"],
            "devices": probe["devices"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "ingraph_step(functional_core)", "error": str(err)[:160]}))

    # window_close row (ISSUE 15): one fleet-agreed window close on a
    # 4-metric suite — collectives_per_close_live is what sweep_regress
    # gates round over round (a close issuing more than one payload
    # collective means the coalesced stride merge broke apart into
    # per-state gathers); the full close methodology (staged stride
    # updates, counted fake 3-rank world) lives in bench.py
    # bench_window_close, reused here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_window_close()
        row = {
            "metric": "window_close(streaming)",
            "mode": "sync",
            "updates_per_s": round(probe["closes_per_s"], 1),
            "ms_per_close": round(probe["ms_per_close"], 3),
            "record_bytes": probe["record_bytes"],
            "collectives_per_close": round(probe["collectives_per_close"], 4),
            "collectives_per_close_live": round(probe["collectives_per_close_live"], 4),
            "latency_ms": probe["latency_ms"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "window_close(streaming)", "error": str(err)[:160]}))

    # arena_suites row (ISSUE 17): N concurrent suites as ONE MetricArena —
    # arena_speedup_100k (the ratio over the per-instance loop at the 100k
    # tier) and retraces_per_add are what sweep_regress gates round over
    # round (a speedup collapse means the vmapped lane fell back to
    # per-tenant dispatch; a retrace growth means the slab-bucket shape
    # discipline broke); the tier methodology (sampled loop extrapolation,
    # counted engine builds) lives in bench.py bench_arena_suites, reused
    # here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_arena_suites()
        tiers = probe["tiers"]
        tier_keys = sorted(tiers, key=int)
        top = tiers[tier_keys[-1]]
        mid = tiers[tier_keys[-2]] if len(tier_keys) > 1 else top
        row = {
            "metric": "arena_suites(arena)",
            "mode": "sync",
            "updates_per_s": top["suites_per_s"],
            "arena_speedup_100k": mid["vs_loop"],
            "builds_top_tier": top["builds"],
            "retraces_per_add": probe["retraces_per_add"],
            "slab_record_bytes": probe["slab_record_bytes"],
            "loop_suites_per_s": probe["loop_suites_per_s"],
            "tiers": tiers,
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "arena_suites(arena)", "error": str(err)[:160]}))

    # ingest_gateway row (ISSUE 19): the admission-controlled front door —
    # ingest_shed_fraction_2x and accounting_exact are what sweep_regress
    # gates round over round (--ingest-shed-ceiling: a gateway shedding
    # more than the overload excess is throwing away admissible load; a
    # broken settlement identity is a correctness failure, not a perf
    # regression); admitted throughput and the per-offer latency
    # distribution ride along. Methodology (pinned-schema fast path,
    # exactly-2x burst against a bounded watermark) lives in bench.py
    # bench_ingest_gateway, reused here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_ingest_gateway()
        row = {
            "metric": "ingest_gateway(ingest)",
            "mode": "sync",
            "updates_per_s": probe["admitted_updates_per_s"],
            "ingest_shed_fraction_2x": probe["shed_fraction_2x"],
            "accounting_exact": probe["accounting_exact"],
            "tenants": probe["tenants"],
            "payload_rows": probe["payload_rows"],
            "latency_ms": probe["latency_ms"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "ingest_gateway(ingest)", "error": str(err)[:160]}))

    # cold_start row (ISSUE 18): replica replacement with the persistent
    # program cache — warm_boot_compiles is what sweep_regress gates at
    # --warm-boot-compile-ceiling (default 0.0: a warmed replica re-enters
    # the fleet compiling NOTHING); first-result latency cold vs warmed and
    # the replacement wall ride along. Methodology (in-process boots around
    # engine resets; the two-process certification runs in make dryrun)
    # lives in bench.py bench_cold_start, reused here verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_cold_start()
        row = {
            "metric": "cold_start(progcache)",
            "mode": "boot",
            # boots-to-first-result per second on the warmed path: the
            # rate a rolling restart can cycle replicas at
            "updates_per_s": round(1000.0 / probe["warm_first_result_ms"], 1)
            if probe["warm_first_result_ms"] > 0
            else 0.0,
            "cold_first_result_ms": probe["cold_first_result_ms"],
            "warm_first_result_ms": probe["warm_first_result_ms"],
            "first_result_speedup": probe["first_result_speedup"],
            "warm_boot_compiles": probe["warm_boot_compiles"],
            "warm_hits": probe["warm_hits"],
            "cold_compiles": probe["cold_compiles"],
            "cold_stores": probe["cold_stores"],
            "store_bytes": probe["store_bytes"],
            "replacement_wall_ms": probe["replacement_wall_ms"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "cold_start(progcache)", "error": str(err)[:160]}))

    # kernel_attack row (ISSUE 20): the roofline-guided variant sweep over
    # every registered heavy kernel — kernel_min_winner_vs_baseline is what
    # sweep_regress gates at --kernel-utilization-floor (default 1.0: an
    # installed winner may never score below the reference floor); the
    # per-kernel winner/baseline walls and utilizations ride along.
    # Methodology lives in bench.py bench_kernel_attack, reused verbatim.
    try:
        import bench as _bench

        probe = _bench.bench_kernel_attack()
        row = {
            "metric": "kernel_attack(autotune)",
            "mode": "sweep",
            # full variant sweeps per second: the one-time cold-process cost
            # of the whole attack (a warm boot restores the table and pays 0)
            "updates_per_s": probe["sweeps_per_s"],
            "sweep_wall_ms": probe["sweep_wall_ms"],
            "kernel_min_winner_vs_baseline": probe["kernel_min_winner_vs_baseline"],
            "kernels": probe["kernels"],
            "sweeps": probe["sweeps"],
            "candidates": probe["candidates"],
            "disqualified": probe["disqualified"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "kernel_attack(autotune)", "error": str(err)[:160]}))

    # drift_report row (ISSUE 15): one PSI/KS drift computation over two
    # 4096-sample vectors — the psi/ks columns double as a determinism
    # canary (fixed seed, fixed shift: a changed score means the binning
    # or the probability floor changed, not the weather).
    try:
        import bench as _bench

        probe = _bench.bench_drift_report()
        row = {
            "metric": "drift_report(streaming)",
            "mode": "host",
            "updates_per_s": round(probe["reports_per_s"], 1),
            "ms_per_report": round(probe["ms_per_report"], 3),
            "sample_size": probe["sample_size"],
            "psi": round(probe["psi"], 4),
            "ks": round(probe["ks"], 4),
            "latency_ms": probe["latency_ms"],
        }
        results.append(row)
        print(json.dumps(row))
    except Exception as err:  # noqa: BLE001 — a failed bench row is recorded in the row, never silently dropped
        print(json.dumps({"metric": "drift_report(streaming)", "error": str(err)[:160]}))

    # telemetry-armed row (ISSUE 7): the deferred Accuracy loop re-run with
    # the flight recorder ON, exporting + validating a Chrome-trace at the
    # end — pins that a trace-enabled sweep run stays in the deferred rows'
    # throughput band (the bench.py telemetry_overhead row owns the precise
    # armed-vs-disarmed ratio; this row owns "tracing a sweep artifact works")
    try:
        import tempfile

        from metrics_tpu.ops import engine as _tel_engine
        from metrics_tpu.ops import telemetry as _telemetry

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from trace_report import check_trace as _check_trace

        was_armed = _telemetry.armed
        _telemetry.set_telemetry(True)
        try:
            data = _data("binary", np.random.RandomState(0))
            jdata = tuple(jax.device_put(jax.numpy.asarray(d)) for d in data)
            jax.block_until_ready(jdata)
            _defer_engine.set_deferred_dispatch(True)
            metric = mt.Accuracy()
            metric.update(*jdata)
            for _ in range(DEFERRED_STEPS):
                metric.update(*jdata)
            jax.block_until_ready(metric.metric_state)
            best = float("inf")
            for _ in range(TRIALS):
                metric.reset()
                start = time.perf_counter()
                for _ in range(DEFERRED_STEPS):
                    metric.update(*jdata)
                jax.block_until_ready(metric.metric_state)
                best = min(best, time.perf_counter() - start)
            trace_path = os.path.join(tempfile.mkdtemp(prefix="mt-sweep-trace-"), "sweep.json")
            n_events = _tel_engine.export_trace(trace_path)
            with open(trace_path) as fh:
                problems = _check_trace(json.load(fh))
            row = {
                "metric": "Accuracy[trace-enabled]",
                "mode": "deferred+telemetry",
                "updates_per_s": round(DEFERRED_STEPS / best, 1),
                "samples_per_s": round(DEFERRED_STEPS * BATCH / best, 1),
                "trace_events": n_events,
                "trace_valid": not problems,
            }
            if problems:
                row["trace_problems"] = problems[:3]
            results.append(row)
            print(json.dumps(row))
        finally:
            _telemetry.set_telemetry(was_armed)
    except Exception as err:
        print(json.dumps({"metric": "Accuracy[trace-enabled]", "error": str(err)[:160]}))

    # reference pass LAST: converting/reading any device value flips the
    # tunneled backend into its post-read regime (~ms per dependent dispatch),
    # which must not poison the pipelined jit rows above — the reference arm
    # therefore reuses the HOST copies of the same data, after all our timing
    ctor_by_name = {name: ctor for name, ctor, _, _ in SWEEP}
    ctor_by_name.update({name: ctor for name, ctor, _, _ in DEFERRED_SWEEP})
    ctor_by_name.update({name: ctor for name, ctor, _, _, _ in HOST_SWEEP})
    for row in results:
        name = row["metric"]
        if name not in np_data_by_name:
            continue
        ref_updates = _time_reference(
            name, ctor_by_name[name], np_data_by_name[name], steps_by_name.get(name, STEPS)
        )
        if ref_updates > 0:
            row["ref_updates_per_s"] = round(ref_updates, 1)
            row["vs_baseline"] = round(row["updates_per_s"] / ref_updates, 2)
            # EVERY sub-1x row must carry an explanation: a curated note, or
            # the row's own measured floor evidence (within 1.6x of a chained
            # program with its exact state profile, same backend regime)
            if (row["vs_baseline"] > 10 or row["vs_baseline"] < 1.0) and name in OUTLIER_NOTES:
                row["note"] = OUTLIER_NOTES[name]
            elif row["vs_baseline"] < 1.0 and 0 < row.get("floor_bound_factor", 0) <= 1.6:
                row["note"] = (
                    f"floor-bound: a chained jitted program with this metric's exact "
                    f"state profile costs {row['floor_ms_per_program']} ms through this "
                    f"backend (measured in the row's own regime); the row runs within "
                    f"{row['floor_bound_factor']}x of that — the gap to the torch-CPU "
                    "baseline is the backend's per-program cost, not metric code"
                )
            print(json.dumps({"metric": name, "ref_updates_per_s": row["ref_updates_per_s"], "vs_baseline": row["vs_baseline"]}))
    summary = None
    if results:
        with_ratio = [r["vs_baseline"] for r in results if "vs_baseline" in r]
        summary = {
            "metric": "SWEEP_SUMMARY",
            "n": len(results),
            "median_updates_per_s": round(
                float(np.median([r["updates_per_s"] for r in results if "updates_per_s" in r])), 1
            ),
            "median_vs_baseline": round(float(np.median(with_ratio)), 2) if with_ratio else None,
            # ANY sub-1x row without a note (curated or measured-floor) is a
            # regression to chase; a fast row (>10x) without a note is
            # covered by the blanket cause
            "unexplained_slow_outliers": [
                r["metric"]
                for r in results
                if "vs_baseline" in r and r["vs_baseline"] < 1.0 and "note" not in r
            ],
            "fast_outliers_blanket_note": FAST_BLANKET_NOTE,
            "baseline_hardware": "torch-cpu (mounted reference), update-only protocol both sides",
            # every exported metric not swept above, with the reason and
            # where its cost IS measured — nothing is silently dropped
            "not_swept": {
                "FID/KID/IS/LPIPS": (
                    "update = feature-extractor forward (Flax InceptionV3 / LPIPS nets); "
                    "benchmarked end-to-end in bench.py fid_wallclock and "
                    "tools/bench_extended.py (fid_128img, fid_scale 1024 images)"
                ),
                "BERTScore/InfoLM": (
                    "require a transformer checkpoint; integration-tested with tiny "
                    "local models (tests/models/test_bert_integration.py) — their cost "
                    "is the embedding forward, a model bench not a metric bench"
                ),
                "MeanAveragePrecision": (
                    "host-side greedy matching by design (reference defers to "
                    "pycocotools); benchmarked in bench.py coco_map_wallclock and "
                    "tools/bench_extended.py (25-500 images)"
                ),
                "PerceptualEvaluationSpeechQuality": (
                    "host wrapper over the pesq C package (absent in this image, "
                    "matching the reference's optional gate); the STOI host wrapper "
                    "gates likewise on pystoi — the NATIVE STOI is swept above"
                ),
                "Metric/CompositionalMetric/MetricCollection/RetrievalMetric": (
                    "base/composition classes, not metrics; suite-level cost is the "
                    "headline fused_suite_update_throughput bench.py workload"
                ),
                "MetricTracker": (
                    "bookkeeping wrapper (increment() snapshots the prior timestep "
                    "as packed journal-record bytes when the metric packs, deepcopy "
                    "fallback otherwise); its per-update cost is the wrapped "
                    "metric's, swept above — the pack cost itself is the "
                    "window_close(streaming) row's record_bytes column"
                ),
            },
            # rows measured on our side whose reference arm cannot run here
            "no_reference_arm": {
                "ROUGEScore": (
                    "the reference's rouge module needs nltk punkt data (absent, "
                    "zero egress) and fails at import; parity is pinned by the "
                    "suite's injected stand-in oracle instead"
                ),
                "ShortTimeObjectiveIntelligibility(native)": (
                    "the reference wraps pystoi (absent); ours is a native "
                    "implementation, standards-locked by its own tests"
                ),
            },
        }
        print(json.dumps(summary))
    if json_out:
        with open(json_out, "w") as handle:
            json.dump({"rows": results, "summary": summary, "config": {
                "batch": BATCH, "classes": C, "steps": STEPS, "trials": TRIALS,
            }}, handle, indent=1)


if __name__ == "__main__":
    main()
