"""Dev tool: generate and insert executable docstring examples.

Given ``{symbol_name: [statement, ...]}``, runs each statement REPL-style on
the pinned CPU backend, captures exactly what an interactive session would
print, formats the transcript as a doctest ``Example:`` block, and inserts it
into the symbol's docstring (before the closing quotes). The suite's doctest
runner (tests/test_doctests.py) then executes the block forever after — this
tool is only for authoring, parity with the reference's doctest-bearing
docstrings (reference `Makefile:22-25` runs every docstring example as a test).

Usage: import from a scratch script, call ``insert_examples(mapping)``.
Statements may be multi-line (compiled in 'single' mode when possible so bare
expressions print their repr, like the REPL).
"""
from __future__ import annotations

import contextlib
import importlib
import inspect
import io
import re
from typing import Dict, List, Sequence


def run_repl(statements: Sequence[str]) -> List[tuple]:
    """Execute statements in a shared namespace, REPL-style; return (src, out) pairs."""
    ns: dict = {}
    pairs = []
    for stmt in statements:
        buf = io.StringIO()
        try:
            code_obj = compile(stmt, "<example>", "single")
        except SyntaxError:
            code_obj = compile(stmt, "<example>", "exec")
        with contextlib.redirect_stdout(buf):
            exec(code_obj, ns)
        out = buf.getvalue()
        if "\n\n" in out.strip("\n"):
            raise ValueError(f"blank line in doctest output of {stmt!r}; pick a different example")
        pairs.append((stmt, out))
    return pairs


def format_block(pairs: Sequence[tuple], indent: str) -> str:
    lines = [f"{indent}Example:"]
    body = indent + "    "
    for src, out in pairs:
        src_lines = src.split("\n")
        lines.append(f"{body}>>> {src_lines[0]}")
        for cont in src_lines[1:]:
            lines.append(f"{body}... {cont}")
        for out_line in out.splitlines():
            lines.append(f"{body}{out_line}" if out_line.strip() else body.rstrip())
    return "\n".join(lines)


def _docstring_span(source: str, obj_name: str) -> tuple:
    """(open_end, close_start, indent) of the docstring of def/class obj_name.

    AST-located so a symbol without a docstring errors instead of hijacking
    the next triple-quote in the file.
    """
    import ast

    tree = ast.parse(source)
    node = next(
        (
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)) and n.name == obj_name
        ),
        None,
    )
    if node is None:
        raise ValueError(f"definition of {obj_name} not found")
    if ast.get_docstring(node) is None:
        raise ValueError(f"no docstring for {obj_name}")
    doc_expr = node.body[0]
    lines = source.splitlines(keepends=True)
    start = sum(len(ln) for ln in lines[: doc_expr.lineno - 1]) + doc_expr.col_offset
    end = sum(len(ln) for ln in lines[: doc_expr.end_lineno - 1]) + doc_expr.end_col_offset
    mo = re.match(r'[rRbBuU]*("""|\'\'\')', source[start:end])
    if not mo:
        raise ValueError(f"{obj_name} docstring is not triple-quoted")
    quotes = mo.group(1)
    open_end = start + mo.end()
    close_start = end - len(quotes)
    indent = " " * (node.col_offset + 4)
    return open_end, close_start, indent


def insert_example(obj, statements: Sequence[str], dry: bool = False) -> str:
    """Run the example and splice it into obj's docstring file. Returns the block."""
    fname = inspect.getsourcefile(obj)
    with open(fname) as fh:
        source = fh.read()
    name = obj.__name__
    if f">>> " in (inspect.getdoc(obj) or ""):
        raise ValueError(f"{name} already has an example")
    open_end, close_start, indent = _docstring_span(source, name)
    pairs = run_repl(statements)
    block = format_block(pairs, indent)
    # works for single- and multi-line docstrings alike: body is re-terminated
    # with a newline + closing-quote indent
    new_body = source[open_end:close_start].rstrip() + "\n\n" + block + "\n" + indent
    new_source = source[:open_end] + new_body + source[close_start:]
    if not dry:
        with open(fname, "w") as fh:
            fh.write(new_source)
    return block


def insert_examples(mapping: Dict[str, Sequence[str]], module: str = "metrics_tpu") -> None:
    mod = importlib.import_module(module)
    done, failed = [], []
    for name, stmts in mapping.items():
        obj = getattr(mod, name)
        try:
            insert_example(obj, stmts)
            done.append(name)
        except Exception as err:  # report and continue: authoring tool
            failed.append((name, repr(err)))
    print(f"inserted: {len(done)}")
    for name, err in failed:
        print(f"FAILED {name}: {err}")
