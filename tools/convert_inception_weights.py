"""Convert torch-fidelity InceptionV3 weights to the metrics_tpu npz format.

Usage:
    python tools/convert_inception_weights.py pt_inception-2015-12-05.pth out.npz
    # then: FrechetInceptionDistance(feature=2048, npz_path="out.npz")

The source checkpoint is torch-fidelity's ``FeatureExtractorInceptionV3``
state dict (the exact weights the reference uses for FID/KID/IS —
`image/fid.py:27-45`). This environment has no network egress, so conversion
runs wherever the .pth already exists; the mapping itself is validated
structurally in `tests/models/test_weight_converter.py` by round-tripping a
synthetic state dict generated from the Flax model's own parameter tree.

Mapping (torch -> flax):
    {m}.conv.weight   (O,I,H,W)  -> params/{m}/conv/kernel   (H,W,I,O)
    {m}.bn.weight                -> params/{m}/bn/scale
    {m}.bn.bias                  -> params/{m}/bn/bias
    {m}.bn.running_mean          -> batch_stats/{m}/bn/mean
    {m}.bn.running_var           -> batch_stats/{m}/bn/var
    fc.weight         (O,I)      -> params/fc/kernel          (I,O)
    fc.bias                      -> params/fc_bias
    *.num_batches_tracked        -> dropped (inference-mode BN)
"""
from __future__ import annotations

import sys
from typing import Dict, Iterable, Optional, Tuple

import numpy as np


def torch_key_to_npz(key: str, value: np.ndarray) -> Optional[Tuple[str, np.ndarray]]:
    """Map one torch state-dict entry to (npz_key, array); None to drop it."""
    if key.endswith("num_batches_tracked"):
        return None
    if key == "fc.weight":
        return "params/fc/kernel", value.transpose(1, 0)
    if key == "fc.bias":
        return "params/fc_bias", value
    prefix = "/".join(key.split(".")[:-2])
    kind, param = key.split(".")[-2:]
    if kind == "conv" and param == "weight":
        return f"params/{prefix}/conv/kernel", value.transpose(2, 3, 1, 0)
    if kind == "bn":
        if param == "weight":
            return f"params/{prefix}/bn/scale", value
        if param == "bias":
            return f"params/{prefix}/bn/bias", value
        if param == "running_mean":
            return f"batch_stats/{prefix}/bn/mean", value
        if param == "running_var":
            return f"batch_stats/{prefix}/bn/var", value
    raise ValueError(f"Unrecognized torch key: {key}")


def npz_key_to_torch(key: str, value: np.ndarray) -> Tuple[str, np.ndarray]:
    """Inverse mapping (used by the structural round-trip test)."""
    parts = key.split("/")
    if key == "params/fc/kernel":
        return "fc.weight", value.transpose(1, 0)
    if key == "params/fc_bias":
        return "fc.bias", value
    space, *mods, layer, param = parts
    prefix = ".".join(mods)
    if layer == "conv" and param == "kernel":
        return f"{prefix}.conv.weight", value.transpose(3, 2, 0, 1)
    if layer == "bn":
        if space == "params":
            return f"{prefix}.bn.{'weight' if param == 'scale' else 'bias'}", value
        return f"{prefix}.bn.running_{param}", value
    raise ValueError(f"Unrecognized npz key: {key}")


def convert_state_dict(state: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for key, value in state.items():
        mapped = torch_key_to_npz(key, np.asarray(value))
        if mapped is not None:
            out[mapped[0]] = mapped[1]
    return out


def main(argv: Iterable[str]) -> None:
    src, dst = argv
    import torch

    state = torch.load(src, map_location="cpu")
    if isinstance(state, dict) and "state_dict" in state:
        state = state["state_dict"]
    if not isinstance(state, dict):
        raise ValueError(f"Unsupported checkpoint format: expected a state dict, got {type(state)}")
    converted = convert_state_dict({k: v.numpy() for k, v in state.items()})
    np.savez(dst, **converted)
    print(f"wrote {len(converted)} arrays to {dst}")


if __name__ == "__main__":
    main(sys.argv[1:])
