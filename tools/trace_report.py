"""Summarize (and validate) a metrics_tpu telemetry trace file.

The trace is the Chrome-trace/Perfetto JSON ``engine.export_trace(path)``
writes (see docs/observability.md): span events per owner track plus the
program ledger under ``programLedger`` and the numeric snapshot under
``snapshot``. This tool turns one into the three summaries an operator (or a
BENCH/SWEEP artifact review) actually reads:

- **top programs** — ledger rows by compile wall time, with FLOPs / bytes
  accessed / peak footprint from XLA cost analysis;
- **collectives** — the sync-face spans (pack, metadata, payload gather,
  unpack, per-state gather) by count, bytes and latency;
- **latency digest** — per-site p50/p95/p99/max from the embedded
  snapshot's full-lifetime histogram plane (``latency_stats``) plus any
  SLO budget violations — the percentiles that survive after the span
  ring has dropped old spans;
- **fault-lane timeline** — every instant mark (faults, ladder demotions/
  promotions, deadline timeouts, degraded serves, journal demotions) in
  monotonic-step order.

Modes::

    python tools/trace_report.py TRACE.json           # full report
    python tools/trace_report.py TRACE.json --check   # validate only (CI)
    python tools/trace_report.py TRACE.json --perf    # step/sync phase
                                                      # decomposition + the
                                                      # roofline ledger (the
                                                      # perf_report() twin)
    python tools/trace_report.py --smoke              # run a small suite with
                                                      # telemetry armed, export,
                                                      # validate, report
    python tools/trace_report.py --diff A B           # counter-delta report
                                                      # between two snapshots
                                                      # or exported traces
    python tools/trace_report.py --fleet-smoke        # simulate a 3-rank fleet,
                                                      # merge + export + validate
                                                      # the multi-rank trace,
                                                      # smoke the --diff path

``--check`` exits non-zero on any structural problem (not valid JSON, missing
or non-monotonic timestamps, malformed events, or a malformed latency
histogram plane: negative bucket counts, ``count`` != the ``+Inf`` bucket,
``sum_s`` inconsistent with count*max, non-monotone percentiles) — the
``make trace`` gate. :func:`check_histogram_exposition` applies the same
family rules to a rendered ``prometheus_text()`` exposition (cumulative
``le`` buckets monotone and ending at ``+Inf`` == ``_count``) — the
validator the ``latency_plane_certification`` runs.
``--diff`` accepts either an ``export_trace``/``export_fleet_trace`` JSON
(its embedded ``snapshot`` is used) or a raw ``telemetry_snapshot()`` dump,
and prints new/removed keys plus the top movers.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Span names that mark the fault lane (instant events on the timeline).
FAULT_MARKS = (
    "fault",
    "ladder-demote",
    "ladder-promote",
    "sync-timeout",
    "sync-degrade-serve",
    "journal-demote",
)

#: Span names that are sync-face collectives/phases.
COLLECTIVE_SITES = (
    "sync-pack",
    "sync-metadata",
    "sync-quantize",
    "sync-payload-gather",
    "sync-unpack",
    "sync-gather",
    "sync-dispatch",
    "sync-force",
    "suite-sync",
    "fleet-gather",
    "fleet-snapshot",
    "fleet-trace",
)


def check_histogram_stats(latency_stats: Any, where: str = "snapshot.latency_stats") -> List[str]:
    """Well-formedness of a ``latency_stats``-shaped histogram plane (the
    per-site blocks ``telemetry.latency_stats()`` / the fleet merge emit):
    non-negative integer bucket counts on strictly-increasing finite ``le``
    bounds ending at ``+Inf``, ``count`` == the bucket total (== the ``+Inf``
    cumulative bucket), ``sum_s`` consistent with ``count``/``max_s``, and
    monotone percentiles. Stdlib-only, like the rest of ``--check``."""
    problems: List[str] = []
    if latency_stats in (None, {}):
        return problems
    if not isinstance(latency_stats, dict):
        return [f"{where} must be an object, got {type(latency_stats).__name__}"]
    for site, block in latency_stats.items():
        tag = f"{where}[{site!r}]"
        if not isinstance(block, dict):
            problems.append(f"{tag} is not an object")
            continue
        buckets = block.get("buckets")
        if not isinstance(buckets, dict) or not buckets:
            problems.append(f"{tag} has no buckets")
            continue
        labels = list(buckets)
        if labels[-1] != "+Inf":
            problems.append(f"{tag} buckets do not end at '+Inf' (last: {labels[-1]!r})")
        bounds = []
        for label in labels[:-1]:
            try:
                bounds.append(float(label))
            except ValueError:
                problems.append(f"{tag} has a non-numeric le label {label!r}")
        if any(b <= 0 for b in bounds) or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            problems.append(f"{tag} le bounds are not positive and strictly increasing")
        counts = list(buckets.values())
        if any((not isinstance(c, int)) or c < 0 for c in counts):
            problems.append(f"{tag} has a negative or non-integer bucket count")
            continue
        count = block.get("count")
        if count != sum(counts):
            problems.append(
                f"{tag} count {count!r} != bucket total {sum(counts)}"
                " (the +Inf cumulative bucket)"
            )
        sum_s = float(block.get("sum_s", 0.0))
        max_s = float(block.get("max_s", 0.0))
        if sum_s < 0:
            problems.append(f"{tag} sum_s is negative")
        if not count and (sum_s or max_s):
            problems.append(f"{tag} is empty but carries sum_s/max_s")
        if count:
            if not (0 < sum_s <= count * max_s * (1 + 1e-9)):
                problems.append(
                    f"{tag} sum_s {sum_s} inconsistent with count {count} * max_s {max_s}"
                )
            p50, p95, p99 = (float(block.get(k, 0.0)) for k in ("p50_s", "p95_s", "p99_s"))
            if not (0 <= p50 <= p95 <= p99 <= max_s * (1 + 1e-9)):
                problems.append(f"{tag} percentiles not monotone: {p50} {p95} {p99} {max_s}")
    return problems


def check_histogram_exposition(text: str) -> List[str]:
    """Validate every ``# TYPE ... histogram`` family in a Prometheus text
    exposition (local ``prometheus_text()`` or the fleet rendering): each
    labelset's ``le`` buckets must be CUMULATIVE (non-decreasing in
    exposition order), end at ``le="+Inf"``, and agree exactly with the
    labelset's ``_count`` sample; ``_sum`` must be present and non-negative
    (zero only for an empty series)."""
    problems: List[str] = []
    hist_families: List[str] = []
    for line in text.splitlines():
        if line.startswith("# TYPE ") and line.rstrip().endswith(" histogram"):
            hist_families.append(line.split(" ")[2])
    if not hist_families:
        return ["no histogram family in the exposition"]
    for fam in hist_families:
        series: Dict[str, List[float]] = {}
        last_le: Dict[str, str] = {}
        counts: Dict[str, float] = {}
        sums: Dict[str, float] = {}
        for line in text.splitlines():
            if line.startswith("#") or " " not in line:
                continue
            name_labels, value = line.rsplit(" ", 1)
            base = name_labels.split("{", 1)[0]
            labels = name_labels[len(base):]
            if base == f"{fam}_bucket":
                le = labels.rsplit('le="', 1)[-1].split('"', 1)[0]
                key = labels.replace(f'le="{le}"', "").strip("{},")
                series.setdefault(key, []).append(float(value))
                last_le[key] = le
            elif base == f"{fam}_count":
                counts[labels.strip("{}")] = float(value)
            elif base == f"{fam}_sum":
                sums[labels.strip("{}")] = float(value)
        if not series:
            problems.append(f"{fam}: histogram family has no _bucket samples")
            continue
        for key, cum in series.items():
            tag = f"{fam}{{{key}}}"
            if any(b - a < 0 for a, b in zip(cum, cum[1:])):
                problems.append(f"{tag}: cumulative le buckets decrease")
            if last_le.get(key) != "+Inf":
                problems.append(f"{tag}: last bucket is not le=\"+Inf\"")
            if key not in counts:
                problems.append(f"{tag}: no _count sample")
            elif counts[key] != cum[-1]:
                problems.append(
                    f"{tag}: _count {counts[key]} != +Inf bucket {cum[-1]}"
                )
            if key not in sums:
                problems.append(f"{tag}: no _sum sample")
            else:
                s = sums[key]
                if s < 0 or (cum[-1] == 0) != (s == 0):
                    problems.append(f"{tag}: _sum {s} inconsistent with count {cum[-1]}")
    return problems


def check_streaming_exposition(text: str) -> List[str]:
    """Validate the streaming-plane families in a fleet exposition
    (``fleet_prometheus_text()``): every ``metrics_tpu_drift_score`` sample
    must carry ``name`` and ``kind`` labels with ``kind`` in {psi, ks} and a
    finite value, and every ``metrics_tpu_metric_value`` sample must carry
    ``name`` and an integer ``window`` label — the same discipline
    ``streaming_monitoring_certification`` asserts end to end."""
    import math

    problems: List[str] = []
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name_labels, value = line.rsplit(" ", 1)
        base = name_labels.split("{", 1)[0]
        if base not in ("metrics_tpu_drift_score", "metrics_tpu_metric_value"):
            continue
        labels = dict(
            part.split("=", 1)
            for part in name_labels[len(base):].strip("{}").split(",")
            if "=" in part
        )
        labels = {k: v.strip('"') for k, v in labels.items()}
        tag = name_labels
        try:
            v = float(value)
        except ValueError:
            problems.append(f"{tag}: non-numeric value {value!r}")
            continue
        if not math.isfinite(v):
            problems.append(f"{tag}: non-finite value")
        if "name" not in labels:
            problems.append(f"{tag}: missing name label")
        if base == "metrics_tpu_drift_score" and labels.get("kind") not in ("psi", "ks"):
            problems.append(f"{tag}: kind label must be psi or ks")
        if base == "metrics_tpu_metric_value" and not labels.get("window", "").isdigit():
            problems.append(f"{tag}: window label must be an integer close id")
    return problems


def check_trace(doc: Any) -> List[str]:
    """Structural validation of one loaded trace document; returns the list
    of problems (empty == valid Chrome-trace JSON with monotonic span
    timestamps)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be an object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    last_ts = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        for field in ("ph", "name", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} ({ev.get('name')!r}) missing {field!r}")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            problems.append(f"event {i} has unknown phase {ph!r}")
        ts = ev.get("ts")
        if ph != "M":
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"event {i} ({ev.get('name')!r}) has bad ts {ts!r}")
            elif last_ts is not None and ts < last_ts:
                problems.append(
                    f"event {i} ({ev.get('name')!r}) ts {ts} < previous {last_ts} (non-monotonic)"
                )
            else:
                last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} ({ev.get('name')!r}) has bad dur {dur!r}")
    ledger = doc.get("programLedger")
    if ledger is not None:
        if not isinstance(ledger, list):
            problems.append("'programLedger' must be a list")
        else:
            for i, row in enumerate(ledger):
                if not isinstance(row, dict) or "kind" not in row:
                    problems.append(f"programLedger row {i} malformed")
    snap = doc.get("snapshot")
    if snap is not None and not isinstance(snap, dict):
        problems.append("'snapshot' must be an object")
    elif snap:
        problems.extend(check_histogram_stats(snap.get("latency_stats")))
    return problems


def _span_rows(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [e for e in doc.get("traceEvents", []) if e.get("ph") in ("X", "i")]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def summarize(doc: Dict[str, Any], top: int = 10) -> str:
    """Render the three operator summaries for one trace document."""
    rows = _span_rows(doc)
    lines: List[str] = []

    # ---- span sites by total time ----
    agg: Dict[str, List[float]] = defaultdict(list)
    for ev in rows:
        if ev["ph"] == "X":
            agg[ev["name"]].append(float(ev.get("dur", 0.0)))
    lines.append(f"== span sites by total time ({len(rows)} events) ==")
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1]))[:top]:
        total = sum(durs) / 1000.0
        # the dispatch caveat belongs NEXT TO the number it qualifies: these
        # spans end when XLA accepts the dispatch, not when the device
        # finishes — reading them as device time is the misread the probed
        # device-dispatch rows exist to correct
        caveat = (
            "  [async host wall — under-measures device; probe with "
            "METRICS_TPU_DEVICE_PROBE_EVERY]"
            if name == "engine-dispatch"
            else ""
        )
        lines.append(
            f"  {name:<22} n={len(durs):<6} total={total:9.3f} ms  "
            f"mean={total / len(durs):8.4f} ms  max={max(durs) / 1000.0:8.4f} ms"
            + caveat
        )
    instants = defaultdict(int)
    for ev in rows:
        if ev["ph"] == "i":
            instants[ev["name"]] += 1
    if instants:
        marks = ", ".join(f"{k}×{v}" for k, v in sorted(instants.items(), key=lambda kv: -kv[1]))
        lines.append(f"  instants: {marks}")

    # ---- top programs (ledger) ----
    ledger = doc.get("programLedger") or []
    lines.append(f"\n== top programs by compile time ({len(ledger)} cached) ==")
    for row in ledger[:top]:
        a = row.get("analysis") or {}
        lines.append(
            f"  {row.get('kind', '?'):<18} key={row.get('key', '')!s:<13} "
            f"compiles={row.get('compiles', 0)} wall={row.get('compile_time_s', 0.0):.3f}s "
            f"hits={row.get('hits', 0)} runs={row.get('donated_runs', 0)}d/{row.get('plain_runs', 0)}p"
            + (
                f"  flops={a.get('flops', 0):.0f} bytes={_fmt_bytes(a.get('bytes_accessed', 0))} "
                f"peak={_fmt_bytes(a.get('peak_bytes', 0))}"
                if a
                else ""
            )
        )

    # ---- collectives by bytes / latency ----
    lines.append("\n== collectives / sync phases ==")
    for site in COLLECTIVE_SITES:
        evs = [e for e in rows if e["name"] == site and e["ph"] == "X"]
        if not evs:
            continue
        total_bytes = sum(float(e.get("args", {}).get("bytes", 0)) for e in evs)
        durs = [float(e.get("dur", 0.0)) for e in evs]
        lines.append(
            f"  {site:<22} n={len(evs):<6} bytes={_fmt_bytes(total_bytes):<12} "
            f"mean={sum(durs) / len(durs) / 1000.0:8.4f} ms  max={max(durs) / 1000.0:8.4f} ms"
        )

    # ---- latency digest (full-lifetime histogram plane) ----
    latency = (doc.get("snapshot") or {}).get("latency_stats") or {}
    lines.append(f"\n== latency digest ({len(latency)} sites, full-lifetime histograms) ==")
    for site, block in sorted(
        latency.items(), key=lambda kv: -float((kv[1] or {}).get("sum_s", 0.0))
    )[:top]:
        lines.append(
            f"  {site:<22} n={int(block.get('count', 0)):<6} "
            f"p50={float(block.get('p50_s', 0.0)) * 1e3:8.3f} ms  "
            f"p95={float(block.get('p95_s', 0.0)) * 1e3:8.3f} ms  "
            f"p99={float(block.get('p99_s', 0.0)) * 1e3:8.3f} ms  "
            f"max={float(block.get('max_s', 0.0)) * 1e3:8.3f} ms"
        )
    slo = (doc.get("snapshot") or {}).get("slo_violations") or {}
    violated = {k: v for k, v in slo.items() if k != "total" and v}
    if violated:
        lines.append(
            "  SLO violations: "
            + ", ".join(f"{k}×{v}" for k, v in sorted(violated.items()))
            + f" (total {slo.get('total', 0)})"
        )

    # ---- window timeline (streaming plane) ----
    streaming = (doc.get("snapshot") or {}).get("streaming") or {}
    windows = streaming.get("windows") or {}
    drift = streaming.get("drift") or {}
    if windows or drift:
        lines.append(f"\n== window timeline ({len(windows)} windows, streaming plane) ==")
        for wname, info in sorted(windows.items()):
            values = info.get("values") or {}
            tail = []
            for wid in sorted(values, key=lambda k: int(k))[-max(top, 5):]:
                val = values[wid] or {}
                if set(val) == {"value"}:
                    shown = f"{float(val['value']):.4g}"
                elif val:
                    shown = "{" + ",".join(f"{k}={float(v):.4g}" for k, v in sorted(val.items())) + "}"
                else:
                    shown = "(non-scalar)"
                tail.append(f"#{wid}={shown}")
            lines.append(
                f"  {wname:<22} window={info.get('window_updates', '?')} "
                f"stride={info.get('stride', '?')} closed={info.get('window', '?')} "
                f"slots={info.get('slots', '?')}  " + "  ".join(tail)
            )
        for dname, scores in sorted(drift.items()):
            lines.append(
                f"  drift {dname:<16} psi={float(scores.get('psi', 0.0)):.4f} "
                f"ks={float(scores.get('ks', 0.0)):.4f} bins={scores.get('bins', '?')}"
            )

    # ---- fault-lane timeline ----
    marks = [e for e in rows if e["name"] in FAULT_MARKS]
    lines.append(f"\n== fault-lane timeline ({len(marks)} marks) ==")
    for ev in marks[: max(top, 20)]:
        args = ev.get("args", {})
        step = args.get("step", "?")
        lane = args.get("lane", "")
        detail = {k: v for k, v in args.items() if k not in ("step", "lane")}
        lines.append(f"  step={step:<6} {ev['name']:<18} lane={lane:<14} {detail}")

    snap = doc.get("snapshot") or {}
    if snap:
        keys = (
            "sync_collectives_issued",
            "sync_bytes_gathered",
            "deferred_steps",
            "deferred_flushes",
            "fault_demotions",
            "fault_promotions",
            "journal_saves",
            "spans_recorded",
        )
        lines.append("\n== snapshot ==")
        lines.append("  " + "  ".join(f"{k}={snap.get(k)}" for k in keys if k in snap))
    return "\n".join(lines)


def perf_summary(doc: Dict[str, Any], top: int = 10) -> str:
    """Render the ISSUE-12 step-latency decomposition from one exported
    trace: the same interval-exclusive phase attribution ``perf_report()``
    computes live, recomputed offline from the file's span events (one
    decomposition per ``pid`` — a merged fleet trace reports per rank and
    in aggregate), plus the sync wire evidence and the ledger's roofline
    rows. Imports the in-package phase map so the offline and live
    decompositions can never disagree."""
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    from metrics_tpu.ops import perf as _perf

    rows_by_pid: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        rows_by_pid[ev.get("pid", 0)].append(
            {
                "site": ev.get("name"),
                "t_start": float(ev.get("ts", 0.0)) / 1e6,
                "dur": float(ev.get("dur", 0.0)) / 1e6,
                "attrs": ev.get("args") or {},
            }
        )
    lines: List[str] = []
    phase_totals: Dict[str, float] = {p: 0.0 for p in _perf.PHASES}
    phase_counts: Dict[str, int] = {p: 0 for p in _perf.PHASES}
    top_level_s = 0.0
    sync_wall_s = 0.0
    wire_bytes = 0
    overlapped_wire_s = 0.0
    forced_wait_s = 0.0
    for pid in sorted(rows_by_pid):
        for rec in _perf._exclusive_spans(rows_by_pid[pid]):
            if rec.get("overlapped"):
                # an in-flight wire span (a sync-dispatch -> sync-force pair
                # brackets it): its wall coexists with host compute — counted
                # in the overlap evidence, NEVER in the phase sums, so the
                # reconciliation against host wall stays within tolerance
                overlapped_wire_s += rec["dur"]
                wire_bytes += int(rec["attrs"].get("bytes", 0) or 0)
                continue
            if rec["site"] == "sync-force":
                forced_wait_s += float(rec["attrs"].get("waited_s", 0.0) or 0.0)
            phase = _perf.SITE_PHASES.get(rec["site"], "host")
            phase_totals[phase] += rec["exclusive_s"]
            phase_counts[phase] += 1
            if phase == "wire":
                wire_bytes += int(rec["attrs"].get("bytes", 0) or 0)
            if rec["top"]:
                top_level_s += rec["dur"]
                if rec["site"] == "suite-sync":
                    sync_wall_s += rec["dur"]
    total = sum(phase_totals.values())
    n_ranks = len(rows_by_pid)
    lines.append(
        f"== step/sync phase decomposition ({n_ranks} rank(s), "
        f"{sum(len(v) for v in rows_by_pid.values())} timed spans, "
        f"{total * 1e3:.3f} ms attributed) =="
    )
    for phase in sorted(phase_totals, key=lambda p: -phase_totals[p]):
        t = phase_totals[phase]
        if t <= 0:
            continue
        share = t / total if total > 0 else 0.0
        lines.append(
            f"  {phase:<12} {t * 1e3:10.3f} ms  {share:6.1%}  "
            f"(n={phase_counts[phase]})"
        )
    sync_attr = sum(
        phase_totals[p] for p in ("pack", "serialize", "wire", "unpack", "orchestrate")
    )
    if sync_wall_s > 0:
        wire_s = phase_totals["wire"]
        bw = (wire_bytes / wire_s / 1e6) if wire_s > 0 else 0.0
        lines.append(
            f"  sync: wall={sync_wall_s * 1e3:.3f} ms attributed={sync_attr * 1e3:.3f} ms "
            f"wire={wire_s * 1e3:.3f} ms ({wire_bytes} B @ {bw:.1f} MB/s effective, "
            f"{wire_s / sync_wall_s:.1%} of sync)"
        )
    if overlapped_wire_s > 0:
        hidden = max(0.0, min(1.0, (overlapped_wire_s - forced_wait_s) / overlapped_wire_s))
        lines.append(
            f"  overlapped wire (async sync): {overlapped_wire_s * 1e3:.3f} ms in flight, "
            f"forced wait {forced_wait_s * 1e3:.3f} ms — wire_hidden_fraction={hidden:.1%}"
        )
    lines.append(
        f"  reconciliation: attributed {total * 1e3:.3f} ms of "
        f"{top_level_s * 1e3:.3f} ms top-level span wall"
        + (f" ({total / top_level_s:.1%})" if top_level_s > 0 else "")
    )

    # ---- roofline ledger rows (from the embedded programLedger) ----
    ledger = [r for r in (doc.get("programLedger") or []) if r.get("roofline")]
    probed = [r for r in ledger if (r["roofline"].get("probes") or 0) > 0]
    lines.append(f"\n== roofline ledger ({len(probed)} probed of {len(ledger)} programs) ==")
    probed.sort(key=lambda r: -(r["roofline"].get("device_p50_s") or 0.0))
    for row in probed[:top]:
        rl = row["roofline"]
        lines.append(
            f"  {row.get('program', row.get('kind', '?')):<36} {rl['bound']:<15} "
            f"p50={rl['device_p50_s'] * 1e3:8.4f} ms  "
            f"{rl['achieved_flops_per_s'] / 1e9:8.3f} GFLOP/s  "
            f"{rl['achieved_bytes_per_s'] / 1e9:8.3f} GB/s  AI={rl['arithmetic_intensity']:.2f}"
        )
    if not probed:
        lines.append(
            "  (no probed programs — arm METRICS_TPU_DEVICE_PROBE_EVERY to fill "
            "the device plane)"
        )
    return "\n".join(lines)


def _flatten_numeric(prefix: str, value: Any) -> Dict[str, float]:
    """Flatten nested dicts to dotted numeric keys (booleans as 0/1; lists
    and strings dropped) — standalone so ``--diff`` works on any two files
    without importing the library."""
    out: Dict[str, float] = {}
    if isinstance(value, bool):
        out[prefix] = 1.0 if value else 0.0
    elif isinstance(value, (int, float)):
        out[prefix] = float(value)
    elif isinstance(value, dict):
        for k, v in value.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(_flatten_numeric(key, v))
    return out


def load_snapshot(path: str) -> Dict[str, float]:
    """Load the numeric snapshot out of ``path``: an ``export_trace`` /
    ``export_fleet_trace`` JSON contributes its embedded ``snapshot``; any
    other JSON object is treated as a raw snapshot dump."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "traceEvents" in doc:
        doc = doc.get("snapshot") or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: no snapshot object found")
    return _flatten_numeric("", {k: v for k, v in doc.items() if k != "failure_log"})


def diff_report(a_path: str, b_path: str, top: int = 10) -> str:
    """Counter-delta report between two snapshots/traces: new and removed
    keys, then the top movers by absolute delta (B - A)."""
    a, b = load_snapshot(a_path), load_snapshot(b_path)
    new = sorted(set(b) - set(a))
    removed = sorted(set(a) - set(b))
    movers = sorted(
        ((k, b[k] - a[k]) for k in set(a) & set(b) if b[k] != a[k]),
        key=lambda kv: (-abs(kv[1]), kv[0]),
    )
    lines = [f"== snapshot diff: {os.path.basename(a_path)} -> {os.path.basename(b_path)} =="]
    lines.append(f"  keys: {len(a)} -> {len(b)}  new={len(new)}  removed={len(removed)}  changed={len(movers)}")
    if new:
        lines.append("  new keys:")
        lines.extend(f"    + {k} = {b[k]:g}" for k in new[:top])
        if len(new) > top:
            lines.append(f"    ... and {len(new) - top} more")
    if removed:
        lines.append("  removed keys:")
        lines.extend(f"    - {k} (was {a[k]:g})" for k in removed[:top])
        if len(removed) > top:
            lines.append(f"    ... and {len(removed) - top} more")
    lines.append(f"  top movers (of {len(movers)}):")
    for k, d in movers[:top]:
        lines.append(f"    {k:<52} {a[k]:>12g} -> {b[k]:<12g} ({'+' if d >= 0 else ''}{d:g})")
    if not movers:
        lines.append("    (no changed keys)")
    return "\n".join(lines)


def run_fleet_smoke(out_path: str) -> str:
    """The ``make trace`` fleet gate: run the local suite cycle, simulate a
    3-rank world at the fleet blob-gather seam (rank 2 deliberately slow in
    the payload-gather phase, both fake ranks clock-skewed), assert the
    straggler report names the slow rank, export the merged one-process-per-
    rank trace, and smoke the ``--diff`` path on two consecutive snapshots.
    The caller validates the written trace with :func:`check_trace`."""
    import copy
    import tempfile

    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    run_smoke(out_path + ".local.json")  # drives a real suite cycle: sync spans + seq anchors

    from metrics_tpu.ops import fleetobs
    from metrics_tpu.parallel import sync as psync

    saved_gather = fleetobs._gather_blobs
    try:

        def fake_gather(blob: bytes, *, owner=None, site="fleet-gather"):
            doc = json.loads(blob.decode("utf-8"))
            rows = [blob]
            for rank, skew_s, slowdown in ((1, 0.002, 1.1), (2, -0.003, 8.0)):
                d = copy.deepcopy(doc)
                if isinstance(d.get("spans"), list):  # the trace gather
                    d["rank"] = rank
                    for s in d["spans"]:
                        s["t_start"] = float(s["t_start"]) + skew_s
                        if s["site"] == "sync-payload-gather":
                            s["dur"] = float(s.get("dur") or 0.0) * slowdown
                else:  # the snapshot gather
                    for block in (d.get("sync_phase_stats") or {}).values():
                        for key in ("total_s", "mean_s", "max_s"):
                            block[key] = float(block.get(key, 0.0)) * slowdown
                    # the full-lifetime plane's gauges slow down too, so the
                    # tail-aware straggler scoring sees the same slow rank
                    # (bucket COUNTS stay untouched: the merge-exactness
                    # assertion below sums them against a per-rank oracle)
                    for lat in (d.get("latency_stats") or {}).values():
                        for key in ("p50_s", "p95_s", "p99_s", "max_s", "sum_s"):
                            lat[key] = float(lat.get(key, 0.0)) * slowdown
                rows.append(json.dumps(d, separators=(",", ":")).encode("utf-8"))
            return rows

        fleetobs._gather_blobs = fake_gather
        psync.set_expected_world(3)

        snap = fleetobs.fleet_snapshot()
        assert snap["world_size"] == 3 and snap["gathered"], "fleet smoke never gathered"
        assert sorted(snap["ranks"]) == [0, 1, 2], sorted(snap["ranks"])
        report = snap["stragglers"]
        assert 2 in report["stragglers"], (
            f"the deliberately-slow rank 2 was not flagged: {report['ranked']}"
        )

        # ---- the fleet histogram merge is EXACT: aggregate bucket counts ==
        # per-rank sums, for every site and every le bucket ----
        agg_lat = snap["aggregate"]["latency_stats"]
        assert agg_lat, "fleet merge carries no latency histograms"
        live_planes = [
            p for p in snap["ranks"].values()
            if isinstance(p, dict) and not (p.get("dead") or p.get("missing") or p.get("corrupt"))
        ]
        for site, block in agg_lat.items():
            per_rank = [b for b in ((p.get("latency_stats") or {}).get(site) for p in live_planes) if b]
            assert block["count"] == sum(int(b.get("count", 0)) for b in per_rank), site
            for label, n_bucket in block["buckets"].items():
                oracle = sum(int((b.get("buckets") or {}).get(label, 0)) for b in per_rank)
                assert n_bucket == oracle, (site, label, n_bucket, oracle)
        # the tail-aware score names the same deliberately-slow rank
        tail_phase = report["phases"]["sync-payload-gather"]
        assert tail_phase.get("tail_slowest_rank") == 2, tail_phase

        n = fleetobs.export_fleet_trace(out_path)
        assert n > 0, "fleet trace exported no span events"

        # ---- the --diff smoke: two consecutive snapshots must show movers ----
        d = tempfile.mkdtemp(prefix="mt-fleet-diff-")
        a_path, b_path = os.path.join(d, "a.json"), os.path.join(d, "b.json")
        with open(a_path, "w", encoding="utf-8") as fh:
            json.dump(snap["aggregate"]["counters"], fh)
        snap2 = fleetobs.fleet_snapshot()
        with open(b_path, "w", encoding="utf-8") as fh:
            json.dump(snap2["aggregate"]["counters"], fh)
        text = diff_report(a_path, b_path)
        # consecutive snapshots must actually MOVE (the gathers themselves
        # advance the collective-slot and span counters); a diff that finds
        # nothing changed means the counter planes froze
        assert "(no changed keys)" not in text, text
        assert " changed=0" not in text.splitlines()[1], text
        print(text)
    finally:
        fleetobs._gather_blobs = saved_gather
        psync.reset_membership()
    return out_path


def run_smoke(out_path: str) -> str:
    """The ``make trace`` driver: run a small 4-metric suite with telemetry
    armed (deferred updates, device probes sampling, one coalesced sync, a
    compute, one journal snapshot), assert the perf decomposition reconciles
    against the measured loop wall, export the trace, and return its path."""
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    import time as _time

    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.ops import engine, telemetry

    telemetry.set_telemetry(True)
    engine.set_device_probe(2)  # sample the device plane through the smoke
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(64).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, 64))
    suite = mt.MetricCollection(
        {
            "acc": mt.Accuracy(),
            "mean": mt.MeanMetric(),
            "mse": mt.MeanSquaredError(),
            "mae": mt.MeanAbsoluteError(),
        }
    )
    try:
        # warmup outside the measured window: first-sight validation + the
        # sync programs compile here, so the measured loop is steady state
        suite.update(p, t)
        suite.sync(distributed_available=lambda: True)
        suite.unsync()
        # ---- the measured perf window: spans must explain this wall ----
        # (update + sync only — compute()'s per-member host math is eager
        # jnp outside the engine, deliberately not a spanned phase)
        telemetry.clear_spans()
        t0 = _time.perf_counter()
        for _ in range(12):
            suite.update(p, t)
        suite.sync(distributed_available=lambda: True)
        suite.unsync()
        wall = _time.perf_counter() - t0
        suite.compute()
        report = mt.perf_report(measured_wall_s=wall)
        recon = report["reconciliation"]
        assert recon["within_tolerance"], (
            f"perf_report phases do not reconcile with the measured wall: {recon}"
        )
        assert report["sync"]["reconciliation"]["within_tolerance"], (
            f"sync phase decomposition does not reconcile: {report['sync']}"
        )
        assert report["sync"]["wire"]["bytes_gathered"] > 0, report["sync"]["wire"]
        assert report["opportunities"], "perf_report ranked no opportunities"
        # ---- the streaming plane: a sliding window with an injected
        # distribution shift, so the export carries window values AND a
        # nonzero drift score ----
        win = mt.Windowed(mt.CatMetric(), window=8, stride=2, name="smoke-window")
        mwin = mt.Windowed(mt.MeanMetric(), window=4, stride=2, name="smoke-mean")
        for i in range(8):
            loc = 0.0 if i < 4 else 4.0
            batch = jnp.asarray(rng.normal(loc, 1.0, 32).astype(np.float32))
            win.update(batch)
            mwin.update(batch)
        win.drift_report()  # newest (shifted) slot vs oldest (pre-shift) slot
        suite.save_state(out_path + ".journal")
        engine.export_trace(out_path)
    finally:
        engine.set_device_probe(None)  # back to the env-driven default (off)
    # the latency digest must be present in the exported snapshot AND in the
    # report text — the `make trace` pin for the full-lifetime plane
    with open(out_path, encoding="utf-8") as fh:
        doc = json.load(fh)
    latency = (doc.get("snapshot") or {}).get("latency_stats") or {}
    assert latency, "--smoke trace carries no latency digest (latency_stats empty)"
    assert "suite-sync" in latency, f"no suite-sync histogram in {sorted(latency)}"
    assert "latency digest" in summarize(doc), "report lost its latency-digest section"
    # the --perf rendering must work offline from the exported file, with a
    # populated decomposition and at least one probed roofline row
    perf_text = perf_summary(doc)
    assert "phase decomposition" in perf_text and "roofline ledger" in perf_text
    assert "probed of" in perf_text and "(0 probed" not in perf_text, perf_text
    # the RENDERED exposition's histogram families must pass the same
    # validator (cumulative le monotone, +Inf == _count, _sum consistent)
    problems = check_histogram_exposition(mt.prometheus_text())
    assert not problems, f"prometheus_text histogram families invalid: {problems[:3]}"
    # the streaming block must round-trip through the export, and the drift
    # families must pass the exposition validator (world size 1: the fleet
    # rendering serves the local plane, zero collectives)
    streaming = (doc.get("snapshot") or {}).get("streaming") or {}
    assert (streaming.get("windows") or {}).get("smoke-window", {}).get("values"), (
        f"--smoke trace lost the streaming window block: {sorted(streaming.get('windows') or {})}"
    )
    assert float((streaming.get("drift") or {}).get("smoke-window", {}).get("psi", 0.0)) > 0, (
        "--smoke drift report carries no shift signal"
    )
    assert "window timeline" in summarize(doc), "report lost its window-timeline section"
    fleet_text = mt.fleet_prometheus_text()
    assert 'metrics_tpu_drift_score{name="smoke-window",kind="psi"}' in fleet_text
    assert 'metrics_tpu_metric_value{name="smoke-mean",window="' in fleet_text
    problems = check_streaming_exposition(fleet_text)
    assert not problems, f"streaming exposition families invalid: {problems[:3]}"
    return out_path


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?", help="path to an export_trace() JSON file")
    ap.add_argument("--check", action="store_true", help="validate only; exit non-zero on problems")
    ap.add_argument(
        "--perf",
        action="store_true",
        help="render the step/sync phase decomposition + roofline ledger "
        "(perf_report()'s offline twin) instead of the standard report",
    )
    ap.add_argument("--top", type=int, default=10, help="rows per summary table")
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="run a small telemetry-armed suite, export, validate and report (the `make trace` gate)",
    )
    ap.add_argument(
        "--fleet-smoke",
        action="store_true",
        help="simulate a 3-rank fleet (straggler flagged), export + validate the merged "
        "multi-rank trace, and smoke the --diff path (the `make trace` fleet gate)",
    )
    ap.add_argument(
        "--diff",
        nargs=2,
        metavar=("A", "B"),
        default=None,
        help="counter-delta report between two exported snapshots/traces (new/removed keys, top movers)",
    )
    ap.add_argument("--out", default=None, help="--smoke/--fleet-smoke: where to write the trace")
    args = ap.parse_args(argv)

    if args.diff:
        try:
            print(diff_report(args.diff[0], args.diff[1], top=args.top))
        except (OSError, ValueError) as err:
            print(f"diff FAILED: {type(err).__name__}: {err}", file=sys.stderr)
            return 1
        return 0

    if args.smoke or args.fleet_smoke:
        import tempfile

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("METRICS_TPU_VALIDATION", "first")
        name = "fleet-trace.json" if args.fleet_smoke else "smoke-trace.json"
        out = args.out or os.path.join(tempfile.mkdtemp(prefix="mt-trace-"), name)
        path = run_fleet_smoke(out) if args.fleet_smoke else run_smoke(out)
        print(f"trace written: {path}")
    elif args.trace:
        path = args.trace
    else:
        ap.error("need a TRACE file, --smoke, --fleet-smoke, or --diff A B")
        return 2

    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as err:
        print(f"trace INVALID: {path}: {type(err).__name__}: {err}", file=sys.stderr)
        return 1

    problems = check_trace(doc)
    if problems:
        print(f"trace INVALID: {path}:", file=sys.stderr)
        for p in problems[:20]:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n_events = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"trace OK: {path} ({n_events} events, {len(doc.get('programLedger') or [])} ledger rows)")
    if args.perf:
        print(perf_summary(doc, top=args.top))
    elif not args.check:
        print(summarize(doc, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
