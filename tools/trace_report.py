"""Summarize (and validate) a metrics_tpu telemetry trace file.

The trace is the Chrome-trace/Perfetto JSON ``engine.export_trace(path)``
writes (see docs/observability.md): span events per owner track plus the
program ledger under ``programLedger`` and the numeric snapshot under
``snapshot``. This tool turns one into the three summaries an operator (or a
BENCH/SWEEP artifact review) actually reads:

- **top programs** — ledger rows by compile wall time, with FLOPs / bytes
  accessed / peak footprint from XLA cost analysis;
- **collectives** — the sync-face spans (pack, metadata, payload gather,
  unpack, per-state gather) by count, bytes and latency;
- **fault-lane timeline** — every instant mark (faults, ladder demotions/
  promotions, deadline timeouts, degraded serves, journal demotions) in
  monotonic-step order.

Modes::

    python tools/trace_report.py TRACE.json           # full report
    python tools/trace_report.py TRACE.json --check   # validate only (CI)
    python tools/trace_report.py --smoke              # run a small suite with
                                                      # telemetry armed, export,
                                                      # validate, report

``--check`` exits non-zero on any structural problem (not valid JSON, missing
or non-monotonic timestamps, malformed events) — the ``make trace`` gate.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Span names that mark the fault lane (instant events on the timeline).
FAULT_MARKS = (
    "fault",
    "ladder-demote",
    "ladder-promote",
    "sync-timeout",
    "sync-degrade-serve",
    "journal-demote",
)

#: Span names that are sync-face collectives/phases.
COLLECTIVE_SITES = (
    "sync-pack",
    "sync-metadata",
    "sync-payload-gather",
    "sync-unpack",
    "sync-gather",
    "suite-sync",
)


def check_trace(doc: Any) -> List[str]:
    """Structural validation of one loaded trace document; returns the list
    of problems (empty == valid Chrome-trace JSON with monotonic span
    timestamps)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be an object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    last_ts = None
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        for field in ("ph", "name", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} ({ev.get('name')!r}) missing {field!r}")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            problems.append(f"event {i} has unknown phase {ph!r}")
        ts = ev.get("ts")
        if ph != "M":
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"event {i} ({ev.get('name')!r}) has bad ts {ts!r}")
            elif last_ts is not None and ts < last_ts:
                problems.append(
                    f"event {i} ({ev.get('name')!r}) ts {ts} < previous {last_ts} (non-monotonic)"
                )
            else:
                last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} ({ev.get('name')!r}) has bad dur {dur!r}")
    ledger = doc.get("programLedger")
    if ledger is not None:
        if not isinstance(ledger, list):
            problems.append("'programLedger' must be a list")
        else:
            for i, row in enumerate(ledger):
                if not isinstance(row, dict) or "kind" not in row:
                    problems.append(f"programLedger row {i} malformed")
    snap = doc.get("snapshot")
    if snap is not None and not isinstance(snap, dict):
        problems.append("'snapshot' must be an object")
    return problems


def _span_rows(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [e for e in doc.get("traceEvents", []) if e.get("ph") in ("X", "i")]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def summarize(doc: Dict[str, Any], top: int = 10) -> str:
    """Render the three operator summaries for one trace document."""
    rows = _span_rows(doc)
    lines: List[str] = []

    # ---- span sites by total time ----
    agg: Dict[str, List[float]] = defaultdict(list)
    for ev in rows:
        if ev["ph"] == "X":
            agg[ev["name"]].append(float(ev.get("dur", 0.0)))
    lines.append(f"== span sites by total time ({len(rows)} events) ==")
    for name, durs in sorted(agg.items(), key=lambda kv: -sum(kv[1]))[:top]:
        total = sum(durs) / 1000.0
        lines.append(
            f"  {name:<22} n={len(durs):<6} total={total:9.3f} ms  "
            f"mean={total / len(durs):8.4f} ms  max={max(durs) / 1000.0:8.4f} ms"
        )
    instants = defaultdict(int)
    for ev in rows:
        if ev["ph"] == "i":
            instants[ev["name"]] += 1
    if instants:
        marks = ", ".join(f"{k}×{v}" for k, v in sorted(instants.items(), key=lambda kv: -kv[1]))
        lines.append(f"  instants: {marks}")

    # ---- top programs (ledger) ----
    ledger = doc.get("programLedger") or []
    lines.append(f"\n== top programs by compile time ({len(ledger)} cached) ==")
    for row in ledger[:top]:
        a = row.get("analysis") or {}
        lines.append(
            f"  {row.get('kind', '?'):<18} key={row.get('key', '')!s:<13} "
            f"compiles={row.get('compiles', 0)} wall={row.get('compile_time_s', 0.0):.3f}s "
            f"hits={row.get('hits', 0)} runs={row.get('donated_runs', 0)}d/{row.get('plain_runs', 0)}p"
            + (
                f"  flops={a.get('flops', 0):.0f} bytes={_fmt_bytes(a.get('bytes_accessed', 0))} "
                f"peak={_fmt_bytes(a.get('peak_bytes', 0))}"
                if a
                else ""
            )
        )

    # ---- collectives by bytes / latency ----
    lines.append("\n== collectives / sync phases ==")
    for site in COLLECTIVE_SITES:
        evs = [e for e in rows if e["name"] == site and e["ph"] == "X"]
        if not evs:
            continue
        total_bytes = sum(float(e.get("args", {}).get("bytes", 0)) for e in evs)
        durs = [float(e.get("dur", 0.0)) for e in evs]
        lines.append(
            f"  {site:<22} n={len(evs):<6} bytes={_fmt_bytes(total_bytes):<12} "
            f"mean={sum(durs) / len(durs) / 1000.0:8.4f} ms  max={max(durs) / 1000.0:8.4f} ms"
        )

    # ---- fault-lane timeline ----
    marks = [e for e in rows if e["name"] in FAULT_MARKS]
    lines.append(f"\n== fault-lane timeline ({len(marks)} marks) ==")
    for ev in marks[: max(top, 20)]:
        args = ev.get("args", {})
        step = args.get("step", "?")
        lane = args.get("lane", "")
        detail = {k: v for k, v in args.items() if k not in ("step", "lane")}
        lines.append(f"  step={step:<6} {ev['name']:<18} lane={lane:<14} {detail}")

    snap = doc.get("snapshot") or {}
    if snap:
        keys = (
            "sync_collectives_issued",
            "sync_bytes_gathered",
            "deferred_steps",
            "deferred_flushes",
            "fault_demotions",
            "fault_promotions",
            "journal_saves",
            "spans_recorded",
        )
        lines.append("\n== snapshot ==")
        lines.append("  " + "  ".join(f"{k}={snap.get(k)}" for k in keys if k in snap))
    return "\n".join(lines)


def run_smoke(out_path: str) -> str:
    """The ``make trace`` driver: run a small 4-metric suite with telemetry
    armed (deferred updates, one coalesced sync, a compute, one journal
    snapshot), export the trace, and return its path."""
    if _REPO_DIR not in sys.path:
        sys.path.insert(0, _REPO_DIR)
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.ops import engine, telemetry

    telemetry.set_telemetry(True)
    rng = np.random.RandomState(0)
    p = jnp.asarray(rng.rand(64).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, 64))
    suite = mt.MetricCollection(
        {
            "acc": mt.Accuracy(),
            "mean": mt.MeanMetric(),
            "mse": mt.MeanSquaredError(),
            "mae": mt.MeanAbsoluteError(),
        }
    )
    for _ in range(12):
        suite.update(p, t)
    suite.sync(distributed_available=lambda: True)
    suite.unsync()
    suite.compute()
    suite.save_state(out_path + ".journal")
    engine.export_trace(out_path)
    return out_path


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?", help="path to an export_trace() JSON file")
    ap.add_argument("--check", action="store_true", help="validate only; exit non-zero on problems")
    ap.add_argument("--top", type=int, default=10, help="rows per summary table")
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="run a small telemetry-armed suite, export, validate and report (the `make trace` gate)",
    )
    ap.add_argument("--out", default=None, help="--smoke: where to write the trace")
    args = ap.parse_args(argv)

    if args.smoke:
        import tempfile

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("METRICS_TPU_VALIDATION", "first")
        out = args.out or os.path.join(tempfile.mkdtemp(prefix="mt-trace-"), "smoke-trace.json")
        path = run_smoke(out)
        print(f"trace written: {path}")
    elif args.trace:
        path = args.trace
    else:
        ap.error("need a TRACE file or --smoke")
        return 2

    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as err:
        print(f"trace INVALID: {path}: {type(err).__name__}: {err}", file=sys.stderr)
        return 1

    problems = check_trace(doc)
    if problems:
        print(f"trace INVALID: {path}:", file=sys.stderr)
        for p in problems[:20]:
            print(f"  - {p}", file=sys.stderr)
        return 1
    n_events = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(f"trace OK: {path} ({n_events} events, {len(doc.get('programLedger') or [])} ledger rows)")
    if not args.check:
        print(summarize(doc, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
