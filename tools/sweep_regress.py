"""Compare two per-metric sweep artifacts and flag regressions.

Round-over-round gate for `tools/bench_sweep.py` output: absolute updates/s
through the tunneled backend swing 2-3x run to run with tunnel latency, so
the comparison is on the **vs-torch-CPU ratios** and on mode changes (a jit
row silently degrading to eager is a regression even at equal throughput).
The ratios themselves still carry noise: two same-code runs measured ratio
swings up to ~4x on individual rows (the torch-CPU reference arm is
host-contention-sensitive, our arm tunnel-latency-sensitive), so the default
threshold sits at 5x — it catches collapses and mode flips, not weather.

    python tools/sweep_regress.py SWEEP_r04.json SWEEP_r05.json
    python tools/sweep_regress.py --threshold 2.5 old.json new.json

Exit 1 when any metric's ratio worsened by more than ``threshold``x, a row's
mode flipped jit->eager, or a previously-present metric disappeared.
"""
from __future__ import annotations

import json
import sys


def compare(old: dict, new: dict, threshold: float = 5.0) -> list:
    old_rows = {r["metric"]: r for r in old["rows"] if "updates_per_s" in r}
    new_rows = {r["metric"]: r for r in new["rows"] if "updates_per_s" in r}
    problems = []
    for name, old_row in old_rows.items():
        new_row = new_rows.get(name)
        if new_row is None:
            problems.append(f"{name}: present in old sweep, missing from new")
            continue
        if old_row["mode"] == "jit" and new_row["mode"] != "jit":
            problems.append(f"{name}: mode regressed jit -> {new_row['mode']}")
        old_ratio, new_ratio = old_row.get("vs_baseline"), new_row.get("vs_baseline")
        if old_ratio:
            if not new_ratio:
                # a collapsed (rounds-to-0) or vanished ratio IS the
                # worst-case regression, not a row to skip
                problems.append(
                    f"{name}: vs_baseline {old_ratio} -> {new_ratio!r} (ratio lost or collapsed)"
                )
            elif old_ratio / new_ratio > threshold:
                problems.append(
                    f"{name}: vs_baseline {old_ratio} -> {new_ratio} ({old_ratio / new_ratio:.1f}x worse)"
                )
    return problems


def main(argv) -> int:
    threshold = 5.0
    if "--threshold" in argv:
        i = argv.index("--threshold")
        try:
            threshold = float(argv[i + 1])
        except (IndexError, ValueError):
            print("usage: sweep_regress.py [--threshold X] OLD.json NEW.json")
            return 2
        argv = argv[:i] + argv[i + 2 :]
    if len(argv) != 2:
        print("usage: sweep_regress.py [--threshold X] OLD.json NEW.json")
        return 2
    with open(argv[0]) as f_old, open(argv[1]) as f_new:
        old, new = json.load(f_old), json.load(f_new)
    problems = compare(old, new, threshold)
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} sweep regression(s) beyond {threshold}x")
        return 1
    n = len([r for r in new["rows"] if "updates_per_s" in r])
    print(f"sweep ok: {n} rows, no ratio regression beyond {threshold}x, no mode downgrades")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
