"""Compare two per-metric sweep artifacts and flag regressions.

Round-over-round gate for `tools/bench_sweep.py` output: absolute updates/s
through the tunneled backend swing 2-3x run to run with tunnel latency, so
the comparison is on the **vs-torch-CPU ratios** and on mode changes (a jit
row silently degrading to eager is a regression even at equal throughput).
The ratios themselves still carry noise: two same-code runs measured ratio
swings up to ~4x on individual rows (the torch-CPU reference arm is
host-contention-sensitive, our arm tunnel-latency-sensitive), so the mean
threshold sits at 5x — it catches collapses and mode flips, not weather.

**Distribution-aware mode** (automatic when both rows carry the
``latency_ms`` percentile column `tools/bench_sweep.py` records through the
telemetry plane's shared histogram): per-call **p50 latency** is far stabler
than the best-of mean — the median ignores the tunnel's tail hiccups that
swing the mean 4x — so p50 ratios gate at ``--p50-threshold`` (default 3x,
tighter than the 5x mean gate). Separately, the **tail ratio** ``p99/p50``
is compared old-vs-new: a row whose median held but whose p99 blew up (a
flush stall, a new lock, a recompile in the loop) fails the
``--tail-threshold`` gate (default 4x growth) even though every mean- and
median-based number looks fine. Rows without percentiles fall back to the
5x mean-ratio gate unchanged, so old artifacts keep comparing.

    python tools/sweep_regress.py SWEEP_r04.json SWEEP_r05.json
    python tools/sweep_regress.py --threshold 2.5 old.json new.json
    python tools/sweep_regress.py --p50-threshold 2.0 --tail-threshold 3.0 old.json new.json
    python tools/sweep_regress.py --explain old.json new.json

**--explain** (ISSUE 12): when a gate fails AND both artifacts archived the
per-row phase columns (``phases_ms`` — per-phase milliseconds recorded from
the telemetry latency plane by ``tools/bench_sweep.py``), each failing row
is additionally ATTRIBUTED: the phase whose absolute delta grew the most is
named with its old -> new milliseconds, so "Accuracy got 4x slower" becomes
"Accuracy got 4x slower *because the compile phase went from 0 to 800 ms*"
— the regressed layer, not just the regressed number. Rows without
archived phase columns say so rather than guessing.

Exit 1 when any metric's ratio worsened by more than ``threshold``x, a p50
latency worsened by more than ``p50-threshold``x, a p99/p50 tail ratio grew
by more than ``tail-threshold``x, a row's mode flipped jit->eager, a
previously-present metric disappeared, a tenant-arena row fell below the
``--arena-speedup-floor`` (default 10x over the per-instance loop at the
100k tier) or started retracing per add (ISSUE 17), or a cold-start row's
``warm_boot_compiles`` rose above ``--warm-boot-compile-ceiling`` (default
0.0 — a warmed replica must re-enter the fleet compiling nothing;
ISSUE 18), or a kernel-attack row's ``kernel_min_winner_vs_baseline`` fell
below ``--kernel-utilization-floor`` (default 1.0 — the autotuner may
never install a variant scoring under the reference floor; ISSUE 20).
"""
from __future__ import annotations

import json
import sys


def _tail_ratio(row: dict) -> float:
    """p99/p50 of a row's latency distribution (0.0 when absent/degenerate)."""
    lat = row.get("latency_ms") or {}
    p50, p99 = float(lat.get("p50", 0.0)), float(lat.get("p99", 0.0))
    return p99 / p50 if p50 > 0 and p99 > 0 else 0.0


def compare(
    old: dict,
    new: dict,
    threshold: float = 5.0,
    p50_threshold: float = 3.0,
    tail_threshold: float = 4.0,
    wire_hidden_floor: float = 0.5,
    close_collective_ceiling: float = 1.0,
    ingraph_collective_ceiling: float = 0.0,
    arena_speedup_floor: float = 10.0,
    warm_boot_compile_ceiling: float = 0.0,
    ingest_shed_ceiling: float = 0.6,
    kernel_utilization_floor: float = 1.0,
) -> list:
    old_rows = {r["metric"]: r for r in old["rows"] if "updates_per_s" in r}
    new_rows = {r["metric"]: r for r in new["rows"] if "updates_per_s" in r}
    problems = []
    # A sweep run without the reference package mounted loses EVERY
    # vs_baseline column at once — that is one environment fact (report it
    # once, still a failure: a baseline cannot silently vanish), not a
    # per-metric regression; per-row ratio checks still fire when only
    # SOME rows lost their ratio.
    old_with_ratio = sum(1 for r in old_rows.values() if r.get("vs_baseline"))
    new_with_ratio = sum(1 for r in new_rows.values() if r.get("vs_baseline"))
    baseline_vanished = old_with_ratio > 0 and new_with_ratio == 0
    if baseline_vanished:
        problems.append(
            f"reference baseline absent from new sweep ({old_with_ratio} old rows "
            f"carried vs_baseline, 0 new rows do — the torch reference was not "
            "mounted for this run; ratio gates skipped, all other gates applied)"
        )
    for name, old_row in old_rows.items():
        new_row = new_rows.get(name)
        if new_row is None:
            problems.append(f"{name}: present in old sweep, missing from new")
            continue
        if old_row["mode"] == "jit" and new_row["mode"] != "jit":
            problems.append(f"{name}: mode regressed jit -> {new_row['mode']}")
        old_ratio, new_ratio = old_row.get("vs_baseline"), new_row.get("vs_baseline")
        if old_ratio and not baseline_vanished:
            if not new_ratio:
                # a collapsed (rounds-to-0) or vanished ratio IS the
                # worst-case regression, not a row to skip
                problems.append(
                    f"{name}: vs_baseline {old_ratio} -> {new_ratio!r} (ratio lost or collapsed)"
                )
            elif old_ratio / new_ratio > threshold:
                problems.append(
                    f"{name}: vs_baseline {old_ratio} -> {new_ratio} ({old_ratio / new_ratio:.1f}x worse)"
                )
        # ---- distribution-aware gates (both rows carry percentiles) ----
        old_p50 = float((old_row.get("latency_ms") or {}).get("p50", 0.0))
        new_p50 = float((new_row.get("latency_ms") or {}).get("p50", 0.0))
        if old_p50 > 0 and new_p50 > 0:
            if new_p50 / old_p50 > p50_threshold:
                problems.append(
                    f"{name}: p50 latency {old_p50} -> {new_p50} ms "
                    f"({new_p50 / old_p50:.1f}x worse, median gate {p50_threshold}x)"
                )
            old_tail, new_tail = _tail_ratio(old_row), _tail_ratio(new_row)
            if old_tail > 0 and new_tail / old_tail > tail_threshold:
                problems.append(
                    f"{name}: tail ratio p99/p50 {old_tail:.1f} -> {new_tail:.1f} "
                    f"({new_tail / old_tail:.1f}x blowup, tail gate {tail_threshold}x)"
                )
        # ---- the async-overlap gate (ISSUE 13): a row that archived
        # wire_hidden_fraction must keep the wire off the critical path —
        # a healthy fraction collapsing below the floor means the overlap
        # broke (the force started blocking out the whole wire again),
        # even when the throughput numbers still look fine ----
        old_wire = old_row.get("wire_hidden_fraction")
        new_wire = new_row.get("wire_hidden_fraction")
        if old_wire is not None and new_wire is not None:
            if float(old_wire) >= wire_hidden_floor and float(new_wire) < wire_hidden_floor:
                problems.append(
                    f"{name}: wire_hidden_fraction {float(old_wire):.2f} -> "
                    f"{float(new_wire):.2f} (below the {wire_hidden_floor} floor — "
                    "the async sync stopped hiding the wire)"
                )
        # ---- the window-close collective gate (ISSUE 15): a row that
        # archived collectives_per_close_live must keep a fleet window
        # close at ONE payload collective — a close issuing more means the
        # coalesced stride merge broke apart into per-state gathers, a
        # collective-budget regression even when every throughput and
        # latency column still looks fine ----
        new_cpc = new_row.get("collectives_per_close_live")
        if new_cpc is not None and float(new_cpc) > close_collective_ceiling:
            old_cpc = old_row.get("collectives_per_close_live")
            problems.append(
                f"{name}: collectives_per_close_live "
                f"{'(unrecorded)' if old_cpc is None else f'{float(old_cpc):.2f}'} -> "
                f"{float(new_cpc):.2f} (above the {close_collective_ceiling} ceiling — "
                "a fleet window close stopped merging in one payload collective)"
            )
        # ---- the in-graph zero-host gate (ISSUE 16): a row that archived
        # host_collectives_per_step made the zero-host-round-trip promise —
        # the ceiling is EXACTLY 0 (default): an in-graph functional-core
        # step that starts issuing host sync collectives, or growing a wire
        # share, silently reintroduced the host protocol it exists to
        # delete, even when every throughput column still looks fine ----
        new_hps = new_row.get("host_collectives_per_step")
        if new_hps is not None and float(new_hps) > ingraph_collective_ceiling:
            old_hps = old_row.get("host_collectives_per_step")
            problems.append(
                f"{name}: host_collectives_per_step "
                f"{'(unrecorded)' if old_hps is None else f'{float(old_hps):.2f}'} -> "
                f"{float(new_hps):.2f} (above the {ingraph_collective_ceiling} ceiling — "
                "the in-graph step started paying host round trips)"
            )
        new_ws = new_row.get("wire_share")
        if new_ws is not None and float(new_ws) > ingraph_collective_ceiling:
            problems.append(
                f"{name}: wire_share {float(new_ws):.4f} (above the "
                f"{ingraph_collective_ceiling} ceiling — the in-graph step "
                "grew a host wire phase)"
            )
        # ---- the tenant-arena gates (ISSUE 17): a row that archived
        # arena_speedup_100k made the vmapped-lane promise — the 100k-tier
        # arena must stay ≥ arena_speedup_floor x over the per-instance
        # Python loop (a collapse means tenants fell back to per-suite
        # dispatch), and retraces_per_add must stay under 1 (a new program
        # per add means the slab-bucket shape discipline broke and a
        # million tenants would mean a million compiles) ----
        new_spd = new_row.get("arena_speedup_100k")
        if new_spd is not None and float(new_spd) < arena_speedup_floor:
            old_spd = old_row.get("arena_speedup_100k")
            problems.append(
                f"{name}: arena_speedup_100k "
                f"{'(unrecorded)' if old_spd is None else f'{float(old_spd):.1f}'} -> "
                f"{float(new_spd):.1f} (below the {arena_speedup_floor}x floor — the "
                "vmapped arena lane stopped beating the per-instance loop)"
            )
        new_rpa = new_row.get("retraces_per_add")
        if new_rpa is not None and float(new_rpa) >= 1.0:
            problems.append(
                f"{name}: retraces_per_add {float(new_rpa):.2f} (>= 1: every tenant "
                "add now retraces — the slab-bucketed shape set broke)"
            )
        # ---- the cold-start gate (ISSUE 18): a row that archived
        # warm_boot_compiles made the zero-recompile-restart promise — a
        # warmed replica (persistent progcache + precompile on boot) must
        # serve its whole first traffic ladder without one fresh compile.
        # The ceiling is EXACTLY 0 by default: any rise means a program
        # stopped round-tripping through the store and every rolling
        # restart pays a recompile stall per replica ----
        new_wbc = new_row.get("warm_boot_compiles")
        if new_wbc is not None and float(new_wbc) > warm_boot_compile_ceiling:
            old_wbc = old_row.get("warm_boot_compiles")
            problems.append(
                f"{name}: warm_boot_compiles "
                f"{'(unrecorded)' if old_wbc is None else f'{float(old_wbc):.0f}'} -> "
                f"{float(new_wbc):.0f} (above the {warm_boot_compile_ceiling:g} "
                "ceiling — a warmed boot re-entered the fleet paying fresh "
                "compiles; the persistent program cache stopped covering it)"
            )
        # ---- the ingest-gateway gates (ISSUE 19): a row that archived
        # ingest_shed_fraction_2x made the overload promise — at exactly 2x
        # offered load against the watermark, the shed fraction sits at the
        # overload excess (~0.5); above the ceiling the gateway is throwing
        # away ADMISSIBLE load (watermark accounting or eviction broke). A
        # false accounting_exact is a correctness failure outright: a row
        # whose settlement identity does not balance cannot be trusted on
        # any other column ----
        new_shed = new_row.get("ingest_shed_fraction_2x")
        if new_shed is not None and float(new_shed) > ingest_shed_ceiling:
            old_shed = old_row.get("ingest_shed_fraction_2x")
            problems.append(
                f"{name}: ingest_shed_fraction_2x "
                f"{'(unrecorded)' if old_shed is None else f'{float(old_shed):.2f}'} -> "
                f"{float(new_shed):.2f} (above the {ingest_shed_ceiling:g} ceiling — "
                "the gateway sheds more than the 2x-overload excess: "
                "admissible load is being thrown away)"
            )
        new_exact = new_row.get("accounting_exact")
        if new_exact is not None and not bool(new_exact):
            problems.append(
                f"{name}: accounting_exact false (the ingest settlement "
                "identity offered == admitted + coalesced + shed + "
                "quarantined broke — rows were double-counted or dropped "
                "from the books)"
            )
        # ---- the kernel-attack gate (ISSUE 20): a row that archived
        # kernel_min_winner_vs_baseline made the autotuner's promise — an
        # installed winner scores at least the reference variant on the
        # roofline (the reference is the selection floor by construction).
        # A ratio below the floor means the selection machinery installed a
        # slower formulation: the sweep's scoring or install logic broke ----
        new_kmin = new_row.get("kernel_min_winner_vs_baseline")
        if new_kmin is not None and float(new_kmin) < kernel_utilization_floor:
            old_kmin = old_row.get("kernel_min_winner_vs_baseline")
            problems.append(
                f"{name}: kernel_min_winner_vs_baseline "
                f"{'(unrecorded)' if old_kmin is None else f'{float(old_kmin):.3f}'} -> "
                f"{float(new_kmin):.3f} (below the {kernel_utilization_floor:g} floor — "
                "the autotuner installed a variant scoring under the "
                "reference; the selection floor broke)"
            )
    return problems


def _row_phases(row: dict) -> dict:
    """The archived per-phase milliseconds of one sweep row (``phases_ms``;
    the sync rows spell it ``coalesced_phases_ms``). Empty when the artifact
    predates the phase columns."""
    p = row.get("phases_ms") or row.get("coalesced_phases_ms") or {}
    return {k: float(v) for k, v in p.items()} if isinstance(p, dict) else {}


def explain(old: dict, new: dict, problems: list) -> list:
    """Attribute each failing row to the phase whose delta moved: one line
    per problem row naming the phase with the largest absolute millisecond
    growth between the archived ``phases_ms`` columns (old -> new). Rows
    without phase columns in BOTH artifacts report that explicitly."""
    old_rows = {r["metric"]: r for r in old.get("rows", ()) if "metric" in r}
    new_rows = {r["metric"]: r for r in new.get("rows", ()) if "metric" in r}
    lines = []
    for name in sorted({p.split(":", 1)[0] for p in problems}):
        o, n = old_rows.get(name), new_rows.get(name)
        if o is None or n is None:
            continue
        op, np_ = _row_phases(o), _row_phases(n)
        if not op or not np_:
            lines.append(
                f"{name}: no archived phase columns to attribute "
                "(re-record with tools/bench_sweep.py to enable --explain)"
            )
            continue
        deltas = {p: np_.get(p, 0.0) - op.get(p, 0.0) for p in set(op) | set(np_)}
        worst = max(deltas, key=lambda p: deltas[p])
        if deltas[worst] <= 0:
            lines.append(f"{name}: no phase grew (phase columns stable; the "
                         "regression is outside the instrumented phases)")
            continue
        grew = sorted(
            ((p, d) for p, d in deltas.items() if d > 0), key=lambda kv: -kv[1]
        )
        detail = ", ".join(f"{p} {op.get(p, 0.0):.3f}->{np_.get(p, 0.0):.3f} ms" for p, _ in grew[:3])
        lines.append(
            f"{name}: regressed phase: {worst} "
            f"(+{deltas[worst]:.3f} ms; movers: {detail})"
        )
    return lines


def _pop_flag(argv: list, flag: str, default: float):
    if flag not in argv:
        return argv, default, True
    i = argv.index(flag)
    try:
        value = float(argv[i + 1])
    except (IndexError, ValueError):
        return argv, default, False
    return argv[:i] + argv[i + 2:], value, True


_USAGE = (
    "usage: sweep_regress.py [--threshold X] [--p50-threshold X] "
    "[--tail-threshold X] [--wire-hidden-floor X] "
    "[--close-collective-ceiling X] [--ingraph-collective-ceiling X] "
    "[--arena-speedup-floor X] [--warm-boot-compile-ceiling X] "
    "[--ingest-shed-ceiling X] [--kernel-utilization-floor X] "
    "[--explain] OLD.json NEW.json"
)


def main(argv) -> int:
    argv = list(argv)
    do_explain = "--explain" in argv
    if do_explain:
        argv.remove("--explain")
    argv, threshold, ok1 = _pop_flag(argv, "--threshold", 5.0)
    argv, p50_threshold, ok2 = _pop_flag(argv, "--p50-threshold", 3.0)
    argv, tail_threshold, ok3 = _pop_flag(argv, "--tail-threshold", 4.0)
    argv, wire_floor, ok4 = _pop_flag(argv, "--wire-hidden-floor", 0.5)
    argv, close_ceiling, ok5 = _pop_flag(argv, "--close-collective-ceiling", 1.0)
    argv, ingraph_ceiling, ok6 = _pop_flag(argv, "--ingraph-collective-ceiling", 0.0)
    argv, arena_floor, ok7 = _pop_flag(argv, "--arena-speedup-floor", 10.0)
    argv, warm_boot_ceiling, ok8 = _pop_flag(argv, "--warm-boot-compile-ceiling", 0.0)
    argv, ingest_shed_ceiling, ok9 = _pop_flag(argv, "--ingest-shed-ceiling", 0.6)
    argv, kernel_floor, ok10 = _pop_flag(argv, "--kernel-utilization-floor", 1.0)
    if not (ok1 and ok2 and ok3 and ok4 and ok5 and ok6 and ok7 and ok8 and ok9 and ok10) or len(argv) != 2:
        print(_USAGE)
        return 2
    with open(argv[0]) as f_old, open(argv[1]) as f_new:
        old, new = json.load(f_old), json.load(f_new)
    problems = compare(
        old,
        new,
        threshold,
        p50_threshold,
        tail_threshold,
        wire_floor,
        close_ceiling,
        ingraph_ceiling,
        arena_floor,
        warm_boot_ceiling,
        ingest_shed_ceiling,
        kernel_floor,
    )
    if problems:
        print("\n".join(problems))
        if do_explain:
            attributions = explain(old, new, problems)
            if attributions:
                print("\n-- attribution (--explain) --")
                print("\n".join(attributions))
        print(f"\n{len(problems)} sweep regression(s) beyond the gates")
        return 1
    rows = [r for r in new["rows"] if "updates_per_s" in r]
    with_pct = sum(1 for r in rows if (r.get("latency_ms") or {}).get("p50"))
    print(
        f"sweep ok: {len(rows)} rows ({with_pct} with percentile columns), no ratio "
        f"regression beyond {threshold}x, no p50 regression beyond {p50_threshold}x, "
        f"no p99/p50 tail blowup beyond {tail_threshold}x, no mode downgrades"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
