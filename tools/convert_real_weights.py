"""One-command conversion of every recognized real checkpoint in a directory.

Usage:
    python tools/convert_real_weights.py /path/to/weights_dir
    # or: make convert-weights WEIGHTS=/path/to/weights_dir

Scans the directory for the artifacts the reference implementation downloads
(reference authority chain: torch-fidelity InceptionV3 `image/fid.py:41-58`,
`lpips` package nets `image/lpip.py:24-77`, HF transformer dirs
`text/bert.py:171-205`) and converts each to this framework's flat ``.npz``
next to the source:

    *inception*.pth        -> inception.npz   (tools/convert_inception_weights.py)
    lpips_<net>*.pth       -> lpips_<net>.npz (tools/convert_lpips_weights.py)
    <dir with config.json> -> used directly by BERTScore/InfoLM (no conversion)

Already-converted ``.npz`` files are left untouched. The converted outputs
are exactly what ``METRICS_TPU_REAL_WEIGHTS=<dir> pytest
tests/models/test_real_weights.py`` consumes.
"""
from __future__ import annotations

import os
import sys
from pathlib import Path


def convert_dir(weights_dir: str) -> list:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import numpy as np
    import torch

    from convert_inception_weights import convert_state_dict as convert_inception
    from convert_lpips_weights import convert_state_dict as convert_lpips

    root = Path(weights_dir)
    done = []
    for pth in sorted(root.glob("*.pth")):
        name = pth.name.lower()
        if "inception" in name:
            out, convert = root / "inception.npz", convert_inception
        elif name.startswith("lpips_"):
            net = name.split("_", 1)[1].split(".")[0].split("-")[0]
            out, convert = root / f"lpips_{net}.npz", lambda s, n=net: convert_lpips(n, s)
        else:
            continue  # unrecognized artifact: leave it alone
        if out.exists():
            continue  # converted already — don't re-load a multi-hundred-MB file
        loaded = torch.load(pth, map_location="cpu")
        if not hasattr(loaded, "items"):
            continue  # not a flat state dict (e.g. a pickled full module)
        state = {
            k: v.numpy() if hasattr(v, "numpy") else np.asarray(v) for k, v in loaded.items()
        }
        np.savez(out, **convert(state))
        done.append(str(out))
    return done


if __name__ == "__main__":
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    converted = convert_dir(sys.argv[1])
    print("converted:" if converted else "nothing new to convert", *converted, sep="\n  ")
